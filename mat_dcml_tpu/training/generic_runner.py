"""Generic rollout-train runner for TimeStep-protocol envs (MPE, toy, ...).

The JAX analogue of the reference's per-benchmark runners
(``mpe_runner.py:20-130``, ``base_runner.py:17-265`` algorithm dispatch):
one episode-chunk loop alternating a jitted collect with a jitted train,
host-side code only for logging/checkpointing.  Algorithm dispatch covers the
full MAT family — vanilla MAT, MAT-Dec (``dec_actor``), and the
encoder/decoder/GRU ablations (``mat_encoder.py``, ``mat_decoder.py``,
``mat_gru.py``) — plus the MLP actor-critic family (MAPPO / IPPO).

Restore-at-construction: ``RunConfig.model_dir`` reloads the latest (or a
specific) checkpoint before training, continuing the episode counter — the
reference's ``--model_dir`` restore (``base_runner.py:264-265``) upgraded to
full-state resume (optimizer + ValueNorm included, training/checkpoint.py).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional

import jax
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.spaces import Discrete
from mat_dcml_tpu.models.actor_critic import ACConfig, ActorCriticPolicy
from mat_dcml_tpu.models.mat import DISCRETE, MATConfig
from mat_dcml_tpu.models.mat_variants import DecoderPolicy, EncoderPolicy, GRUPolicy
from mat_dcml_tpu.models.policy import TransformerPolicy
from mat_dcml_tpu.training.ac_rollout import ACRolloutCollector
from mat_dcml_tpu.training.checkpoint import CheckpointManager
from mat_dcml_tpu.training.ippo import IPPOTrainer
from mat_dcml_tpu.training.mappo import Bootstrap, MAPPOConfig, MAPPOTrainer
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig
from mat_dcml_tpu.training.rollout import RolloutCollector

MAT_FAMILY = ("mat", "mat_dec", "mat_encoder", "mat_decoder", "mat_gru")
AC_FAMILY = ("mappo", "rmappo", "ippo")
SUPPORTED_ALGOS = MAT_FAMILY + AC_FAMILY


def build_discrete_policy(run: RunConfig, env):
    """Algorithm -> policy for a discrete-action TimeStep env
    (``transformer_policy.py:66-79`` model-class dispatch)."""
    cfg = MATConfig(
        n_agent=env.n_agents,
        obs_dim=env.obs_dim,
        state_dim=env.share_obs_dim,
        action_dim=env.action_dim,
        n_block=run.n_block,
        n_embd=run.n_embd,
        n_head=run.n_head,
        action_type=DISCRETE,
        encode_state=run.encode_state,
        dec_actor=run.dec_actor or run.algorithm_name == "mat_dec",
        share_actor=run.share_actor or run.algorithm_name == "mat_dec",
        n_objective=run.n_objective,
    )
    if run.algorithm_name in ("mat", "mat_dec"):
        return TransformerPolicy(cfg)
    if run.algorithm_name == "mat_encoder":
        return EncoderPolicy(cfg)
    if run.algorithm_name == "mat_decoder":
        return DecoderPolicy(cfg)
    if run.algorithm_name == "mat_gru":
        return GRUPolicy(cfg)
    raise NotImplementedError(
        f"algorithm_name={run.algorithm_name!r}; MAT family: {MAT_FAMILY}"
    )


class GenericRunner:
    """Collect/train loop with episode-reward accounting for any TimeStep env."""

    def __init__(self, run: RunConfig, ppo: PPOConfig, env, log_fn=print):
        if run.algorithm_name not in SUPPORTED_ALGOS:
            raise NotImplementedError(
                f"algorithm_name={run.algorithm_name!r}; supported: {SUPPORTED_ALGOS}"
            )
        self.run_cfg = run
        self.env = env
        self.log = log_fn
        self.is_mat = run.algorithm_name in MAT_FAMILY

        if self.is_mat:
            self.policy = build_discrete_policy(run, env)
            self.trainer = MATTrainer(self.policy, ppo, total_updates=run.episodes)
            self.collector = RolloutCollector(env, self.policy, run.episode_length)
        else:
            ac = ACConfig(
                hidden_size=run.n_embd,
                use_recurrent_policy=run.algorithm_name == "rmappo",
            )
            self.policy = ActorCriticPolicy(
                ac,
                obs_dim=env.obs_dim,
                cent_obs_dim=env.obs_dim if run.algorithm_name == "ippo" else env.share_obs_dim,
                space=Discrete(env.action_dim),
            )
            mcfg = MAPPOConfig(
                lr=ppo.lr, critic_lr=ppo.lr, ppo_epoch=ppo.ppo_epoch,
                num_mini_batch=ppo.num_mini_batch, entropy_coef=ppo.entropy_coef,
                use_recurrent_policy=run.algorithm_name == "rmappo",
            )
            trainer_cls = IPPOTrainer if run.algorithm_name == "ippo" else MAPPOTrainer
            self.trainer = trainer_cls(self.policy, mcfg)
            self.collector = ACRolloutCollector(
                env, self.policy, run.episode_length,
                use_local_value=run.algorithm_name == "ippo",
            )

        self._collect = jax.jit(self.collector.collect)
        self._train = jax.jit(self.trainer.train)

        self.run_dir = (
            Path(run.run_dir) / run.env_name / run.scenario / run.algorithm_name / run.experiment_name
        )
        self.ckpt = CheckpointManager(self.run_dir / "models")
        self.metrics_path = self.run_dir / "metrics.jsonl"
        self.start_episode = 0

    def setup(self, seed: Optional[int] = None):
        seed = self.run_cfg.seed if seed is None else seed
        key = jax.random.key(seed)
        k_model, k_roll = jax.random.split(key)
        params = self.policy.init_params(k_model)
        train_state = self.trainer.init_state(params)
        if self.run_cfg.model_dir:
            mgr = CheckpointManager(self.run_cfg.model_dir)
            restored = mgr.restore(template=train_state)
            if restored is None:
                raise FileNotFoundError(f"no checkpoint under {self.run_cfg.model_dir}")
            train_state = restored
            self.start_episode = (mgr.latest_step or 0) + 1
            self.log(f"restored checkpoint step {mgr.latest_step} from {self.run_cfg.model_dir}")
        rollout_state = self.collector.init_state(k_roll, self.run_cfg.n_rollout_threads)
        return train_state, rollout_state

    def _bootstrap(self, rs):
        if self.is_mat:
            return rs
        cent = rs.obs if self.collector.use_local_value else rs.share_obs
        return Bootstrap(cent_obs=cent, critic_h=rs.critic_h, mask=rs.mask)

    def train_loop(self, num_episodes: Optional[int] = None, train_state=None, rollout_state=None):
        run = self.run_cfg
        episodes = num_episodes if num_episodes is not None else run.episodes
        if train_state is None:
            train_state, rollout_state = self.setup()
        key = jax.random.key(run.seed + 7919)

        start = time.time()
        for episode in range(self.start_episode, episodes):
            rollout_state, traj = self._collect(train_state.params, rollout_state)
            key, k_train = jax.random.split(key)
            train_state, metrics = self._train(
                train_state, traj, self._bootstrap(rollout_state), k_train
            )

            total_steps = (episode + 1) * run.episode_length * run.n_rollout_threads
            if episode % run.log_interval == 0:
                rew = np.asarray(traj.rewards)
                elapsed = time.time() - start
                # fps counts only steps run in THIS process (correct after a
                # --model_dir resume, where total_steps includes prior runs)
                steps_here = (episode + 1 - self.start_episode) * run.episode_length * run.n_rollout_threads
                record = {
                    "episode": episode,
                    "total_steps": total_steps,
                    "fps": steps_here / max(elapsed, 1e-9),
                    "average_step_rewards": float(rew.mean()),
                    "value_loss": float(metrics.value_loss),
                    "policy_loss": float(metrics.policy_loss),
                    "dist_entropy": float(metrics.dist_entropy),
                }
                self.metrics_path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.metrics_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
                self.log(
                    f"ep {episode} steps {total_steps} fps {record['fps']:.0f} "
                    f"avg_r {record['average_step_rewards']:.3f} "
                    f"vloss {record['value_loss']:.3f} ploss {record['policy_loss']:.3f}"
                )

            if episode % run.save_interval == 0 or episode == episodes - 1:
                self.ckpt.save(episode, train_state)

        return train_state, rollout_state
