"""MAT/MAPPO PPO trainer as a single jitted update.

Reference: ``mat_src/mat/algorithms/mat/mat_trainer.py``.  The torch epoch /
minibatch Python loops become ``lax.scan``s; Adam + grad-clip become optax;
ValueNorm is explicit pytree state.

Faithfully kept (flag-gated) reference behaviors:
- per-epoch return recomputation + advantage re-normalization *inside* the
  PPO epoch loop (``mat_trainer.py:178-198``) — the reference's distinctive
  divergence from upstream MAT; ``recompute_returns_per_epoch=False`` gives
  the upstream compute-once behavior.
- ValueNorm statistics update before normalize inside the value loss
  (``mat_trainer.py:68-71``), per minibatch.
- clipped + huber value loss with active-mask weighting
  (``mat_trainer.py:54-94``), clipped surrogate summed over the action dim
  (``mat_trainer.py:129-139``).

Under ``pjit`` over a data mesh the batch statistics (advantage mean/std,
ValueNorm moments) are computed with plain ``jnp.mean`` on sharded arrays —
XLA inserts the cross-device reductions, which is the TPU-native replacement
for the reference's single-device numpy statistics (SURVEY.md §2.8).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from mat_dcml_tpu.models.policy import TransformerPolicy
from mat_dcml_tpu.telemetry.scopes import named_scope, probe
from mat_dcml_tpu.ops.distributions import huber_loss
from mat_dcml_tpu.ops.gae import compute_gae, compute_gae_chunked
from mat_dcml_tpu.ops.normalize import (
    ValueNormState,
    value_norm_denormalize,
    value_norm_init,
    value_norm_normalize,
    value_norm_update,
)
from mat_dcml_tpu.training.minibatch import (
    check_layout,
    effective_accum,
    largest_divisor_leq,
    permute_rows,
    slice_rows,
)
from mat_dcml_tpu.training.rollout import RolloutState, Trajectory


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    """Hyperparameters; defaults follow the DCML training recipe
    (``DCML_MAT_Train.py:193`` + ``config.py:156-315``)."""

    lr: float = 5e-5
    opti_eps: float = 1e-5
    weight_decay: float = 0.0
    clip_param: float = 0.2
    ppo_epoch: int = 15
    num_mini_batch: int = 4
    entropy_coef: float = 0.01
    value_loss_coef: float = 1.0
    max_grad_norm: float = 10.0
    gamma: float = 0.99
    gae_lambda: float = 0.95
    huber_delta: float = 10.0
    use_clipped_value_loss: bool = True
    use_huber_loss: bool = True
    use_valuenorm: bool = True
    use_popart: bool = False
    use_value_active_masks: bool = True
    use_policy_active_masks: bool = True
    use_max_grad_norm: bool = True
    use_linear_lr_decay: bool = False
    recompute_returns_per_epoch: bool = True  # mat_trainer.py:178-198
    # split each PPO minibatch into this many sequential gradient-accumulation
    # chunks: activation memory drops by the same factor while gradients stay
    # EXACT (chunk losses are normalized by full-minibatch denominators, so
    # the summed chunk gradients equal the unchunked gradient; pinned by
    # tests/test_ppo_accum.py).  The big-batch enabler alongside MATConfig.remat.
    grad_accum_steps: int = 1
    # Recurrent chunk window for the AC families (rmappo/rhappo/rhatrpo;
    # ignored by the MAT trainer): minibatch items are data_chunk_length
    # windows re-run from stored chunk-start hiddens (separated_buffer.py
    # recurrent generator).  Setting it EQUAL to episode_length degenerates
    # to the reference's naive-recurrent generator (full-episode items from
    # the t=0 hidden) — one knob covers both generators.
    data_chunk_length: int = 10
    # ---- byte-diet knobs (Podracer arXiv:2104.06272: stream the learner's
    # working set through small donated buffers) ------------------------------
    # Target number of streamed chunks each PPO minibatch's fwd/bwd runs as
    # (largest divisor of mb_size <= this; 0/1 = monolithic).  Reuses the
    # exact gradient-accumulation machinery — chunk losses are normalized by
    # full-minibatch denominators so summed chunk gradients equal the
    # unchunked gradient up to float summation order.  The XLA-counted bytes
    # of one update shrink ~proportionally (the fwd/bwd scan body is counted
    # once at chunk size); an explicit grad_accum_steps > 1 takes precedence.
    update_stream_chunks: int = 4
    # Time-chunk length for the streamed per-epoch target recompute: GAE runs
    # as a chunked reverse scan (ops/gae.compute_gae_chunked) and the
    # flattened advantage/return rows are assembled E-major chunk-by-chunk
    # into carry buffers instead of two full-size transpose copies per epoch.
    # Bit-exact vs the monolithic path (tests/test_stream_equivalence.py);
    # rounded to the largest divisor of episode_length; 0 = monolithic.
    target_stream_chunk: int = 10
    # Minibatch assembly recipe: "gather" (default; one gather of mb_size
    # rows per minibatch — exact round-4 behavior) or "contiguous" (permute
    # all rows once per epoch into a flat buffer, minibatches are contiguous
    # dynamic_slices; byte-identical minibatch content under the same
    # permutation, but materializes a full permuted copy — trades counted
    # gather traffic for peak memory, which is why it is opt-in).
    minibatch_layout: str = "gather"
    # Host-offload the streamed update's chunk stream (parallel/offload.py):
    # after the (accum, chunk) reshape the chunk stack moves to host memory
    # and each chunk transfers back on-device inside the accumulation scan —
    # the device-resident data working set of the fwd/bwd drops from a full
    # minibatch to one chunk.  Composes with update_stream_chunks (the chunk
    # grain) and remat (the activation side of the same HBM budget); the
    # E=2048 memory-wall knob.  Numerically exact — transfers don't change
    # values (tests/test_stream_equivalence.py pins bit-exactness).  On CPU
    # (single memory space) it traces as a no-op; HBM relief is a chip claim.
    update_offload: bool = False
    # ---- off-policy correction (training/off_policy.py) -------------------
    # Truncation thresholds for the V-trace-style per-timestep importance
    # weights a stale async trajectory carries in ``traj.is_weights`` (raw
    # behavior->target ratios; --staleness_budget > 1).  rho-bar clips the
    # policy-surrogate weight, c-bar the value-loss weight (arXiv:1802.01561
    # notation; 1.0/1.0 is the paper's recommended setting).  Ignored when
    # is_weights is absent — the on-policy loss is untouched.
    vtrace_rho_bar: float = 1.0
    vtrace_c_bar: float = 1.0
    # MO-MAT scalarization weights, comma-separated floats ("99,1" etc.);
    # empty = equal weights.  Reconstruction of the missing ``momat_trainer``
    # around the surviving ``mo_shared_buffer.py`` per-objective GAE.
    # Ignored when the policy has a single objective.
    objective_weights: str = ""
    # How MO advantages are combined (the reference's momat trainer is absent
    # from its tree, so this is the reconstruction's central choice):
    #   True  — scalarize RAW per-channel advantages first, then normalize the
    #           combined advantage once.  The objective channels already carry
    #           the env's alpha/beta scaling (envs/dcml/env.py objectives), so
    #           with equal weights this reproduces the scalar-reward gradient
    #           exactly (GAE is linear) — and the reference's published curves
    #           (payment at -5.2 by 64k steps, momat_payment.csv) match scalar
    #           dynamics, not unit-std per-channel pressure.
    #   False — round-2 behavior: normalize each channel to unit std, then
    #           weight-sum.  Removes the built-in 99:1 scale (payment curve
    #           diverged: -26.9 at 64k vs the reference's -5.2).
    mo_combined_norm: bool = True


class TrainState(NamedTuple):
    params: dict
    opt_state: optax.OptState
    value_norm: ValueNormState
    update_step: jax.Array


class TrainMetrics(NamedTuple):
    value_loss: jax.Array
    policy_loss: jax.Array
    dist_entropy: jax.Array
    grad_norm: jax.Array
    ratio: jax.Array
    # training-health telemetry: post-update parameter norm, |update|/|params|
    # per optimizer step, and a NaN/Inf guard (count of minibatch updates
    # whose global grad norm was non-finite; summed over the whole train call)
    param_norm: jax.Array = 0.0
    update_ratio: jax.Array = 0.0
    nonfinite_grads: jax.Array = 0.0


class MATTrainer:
    """Builds the jittable ``train`` function (``mat_trainer.py:158-217``)."""

    def __init__(self, policy: TransformerPolicy, cfg: PPOConfig, total_updates: int = 1):
        self.policy = policy
        self.cfg = cfg
        self.n_objective = getattr(policy.cfg, "n_objective", 1)
        if cfg.objective_weights:
            w = [float(s) for s in cfg.objective_weights.split(",")]
            if len(w) != self.n_objective:
                raise ValueError(
                    f"objective_weights has {len(w)} entries for {self.n_objective} objectives"
                )
            arr = jnp.asarray(w, jnp.float32)
            # normalize to the simplex so "99,1" and "0.99,0.01" are the same
            # config: combined mode is scale-invariant via the single
            # post-scalarization normalization, per-channel mode because each
            # channel is unit-std before weighting — in both, only weight
            # RATIOS matter
            self.objective_weights = arr / arr.sum()
        else:
            self.objective_weights = jnp.ones((self.n_objective,), jnp.float32) / self.n_objective
        self.total_updates = max(total_updates, 1)
        if cfg.use_linear_lr_decay:
            # update_linear_schedule (mat/utils/util.py:17-21)
            sched = optax.linear_schedule(cfg.lr, 0.0, self.total_updates)
        else:
            sched = cfg.lr
        tx = optax.adam(sched, eps=cfg.opti_eps)
        if cfg.weight_decay:
            tx = optax.chain(optax.add_decayed_weights(cfg.weight_decay), tx)
        if cfg.use_max_grad_norm:
            tx = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm), tx)
        self.tx = tx

    def init_state(self, params) -> TrainState:
        return TrainState(
            params=params,
            opt_state=self.tx.init(params),
            value_norm=value_norm_init(self.n_objective),
            update_step=jnp.zeros((), jnp.int32),
        )

    # ------------------------------------------------------------------ train

    def train_iteration(self, collector, state: TrainState, rollout_state, key: jax.Array):
        """One fused collect+train iteration — the unit ``base_runner``'s
        ``--iters_per_dispatch`` scans over.  Pure and jittable; the MAT
        trainer bootstraps from the post-collect rollout state directly, so
        the composition is exactly the K=1 host loop's two calls.  Returns
        ``(state, rollout_state, metrics, chunk_stats)``."""
        rollout_state, traj = collector.collect(state.params, rollout_state)
        state, metrics = self.train(state, traj, rollout_state, key)
        return state, rollout_state, metrics, traj.chunk_stats

    def train(
        self, state: TrainState, traj: Trajectory, rollout_state: RolloutState, key: jax.Array
    ) -> Tuple[TrainState, TrainMetrics]:
        """One full PPO update over a rollout chunk.  Pure; jit/pjit this."""
        cfg = self.cfg
        T, E = traj.rewards.shape[:2]
        n_rows = T * E
        # The reference also floors and drops remainder rows per epoch
        # (shared_buffer.py:250-261); the assert mirrors its explicit check.
        assert n_rows >= cfg.num_mini_batch, (
            f"PPO needs episode_length*n_rollout_threads ({n_rows}) >= "
            f"num_mini_batch ({cfg.num_mini_batch})"
        )
        mb_size = n_rows // cfg.num_mini_batch

        # Flatten (T, E) -> rows E-MAJOR: under a data-sharded mesh E is the
        # sharded axis, and merging it as the major axis lets the row sharding
        # propagate as a relabel — T-major flatten interleaves shards and
        # forces an [SPMD] involuntary full rematerialization per tensor
        # (MULTICHIP_r03 tail).  Row ORDER is irrelevant to the math: every
        # epoch permutes rows before forming minibatches.
        def flatten_rows(x):
            return x.swapaxes(0, 1).reshape(n_rows, *x.shape[2:])

        # Streamed E-major flatten: identical VALUES to flatten_rows (a
        # transpose is exact), assembled chunk-by-chunk into a scan-carried
        # buffer XLA donates in place, instead of one full-size transpose
        # copy materializing in the per-epoch scope.
        t_chunk = largest_divisor_leq(T, cfg.target_stream_chunk)

        def flatten_rows_streamed(x):
            n_chunks = T // t_chunk
            blocks = x.reshape(n_chunks, t_chunk, E, *x.shape[2:])

            def write(buf, inp):
                c, blk = inp
                blk = blk.swapaxes(0, 1)  # (E, t_chunk, ...)
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, blk, c * t_chunk, axis=1
                ), None

            buf0 = jnp.zeros((E, T, *x.shape[2:]), x.dtype)
            buf, _ = jax.lax.scan(write, buf0, (jnp.arange(n_chunks), blocks))
            return buf.reshape(n_rows, *x.shape[2:])

        flat_src = {
            "share_obs": traj.share_obs,
            "obs": traj.obs,
            "available_actions": traj.available_actions,
            "actions": traj.actions,
            "log_probs": traj.log_probs,
            "values": traj.values,
            "active_masks": traj.active_masks[:-1],
        }
        if traj.is_weights is not None:
            # raw truncated-IS ratios from the async off-policy correction
            # (off_policy.make_vtrace_correction); clipped at rho-bar/c-bar
            # inside loss_fn.  Present on EVERY block of a corrected run so
            # the jitted update's pytree structure never flips mid-run.
            flat_src["is_weights"] = traj.is_weights
        flat = jax.tree.map(flatten_rows, flat_src)

        def compute_targets(params, value_norm):
            with named_scope("train/compute_targets"):
                # bootstrap + GAE (base_runner.compute / mat_trainer.py:180-192)
                next_values = self.policy.get_values(params, rollout_state.share_obs, rollout_state.obs)
                values_all = jnp.concatenate([traj.values, next_values[None]], axis=0)
                if cfg.use_valuenorm or cfg.use_popart:
                    values_all = value_norm_denormalize(value_norm, values_all)
                if cfg.target_stream_chunk > 0:
                    adv, returns = compute_gae_chunked(
                        traj.rewards, values_all, traj.masks,
                        cfg.gamma, cfg.gae_lambda, t_chunk,
                    )
                else:
                    adv, returns = compute_gae(traj.rewards, values_all, traj.masks, cfg.gamma, cfg.gae_lambda)
                if self.n_objective > 1:
                    # scalarization weights: per-step DMO coefficients (broadcast
                    # over agents) when collected, else the static weights
                    if traj.objective_coefficients is not None:
                        w = traj.objective_coefficients[:, :, None, :]  # (T, E, 1, n_obj)
                    else:
                        w = self.objective_weights
                    if cfg.mo_combined_norm:
                        # scalarize RAW advantages before normalizing (see
                        # PPOConfig.mo_combined_norm rationale)
                        adv = (adv * w).sum(-1, keepdims=True)
                # advantage normalization over active entries (mat_trainer.py:193-197);
                # identical to the reference's global statistics when the
                # (remaining) channel count is 1.
                active = traj.active_masks[:-1]
                axes = tuple(range(adv.ndim - 1))
                denom = active.sum()
                mean = (adv * active).sum(axes) / denom
                var = (((adv - mean) ** 2) * active).sum(axes) / denom
                adv_norm = (adv - mean) / (jnp.sqrt(var) + 1e-5)
                if self.n_objective > 1 and not cfg.mo_combined_norm:
                    adv_norm = (adv_norm * w).sum(-1, keepdims=True)
                probe("train/compute_targets",
                      {"advantages": adv_norm, "returns": returns})
                flatten = flatten_rows_streamed if cfg.target_stream_chunk > 0 else flatten_rows
                return flatten(adv_norm), flatten(returns)

        if cfg.grad_accum_steps > 1:
            assert mb_size % cfg.grad_accum_steps == 0, (
                f"grad_accum_steps ({cfg.grad_accum_steps}) must divide the minibatch size "
                f"({mb_size} = {n_rows} rows / {cfg.num_mini_batch} minibatches)"
            )
        # Streamed update: the minibatch fwd/bwd runs as `accum` donated-carry
        # chunks (exact accumulation, full-minibatch denominators).  Besides
        # the grad_accum memory story, this is the byte diet's main course:
        # the chunk-shaped fwd/bwd scan body is what XLA's cost model counts,
        # so counted bytes-per-update drop ~proportionally (BENCHLOG r6 A/B).
        accum = effective_accum(mb_size, cfg.grad_accum_steps, cfg.update_stream_chunks)
        layout = check_layout(cfg.minibatch_layout)

        def apply_minibatch(params, opt_state, value_norm, batch_mb, adv_mb, ret_b):
            # ValueNorm update precedes normalize (mat_trainer.py:68-71),
            # always on the FULL minibatch regardless of accumulation
            if cfg.use_valuenorm or cfg.use_popart:
                value_norm = value_norm_update(value_norm, ret_b.reshape(-1, ret_b.shape[-1]))

            # full-minibatch denominators: per-chunk losses scaled by these
            # sum to the unchunked loss, so accumulated gradients are exact
            active_full_sum = batch_mb["active_masks"].sum()

            def loss_fn(params, chunk):
                batch, adv_b, ret_chunk = chunk
                if cfg.use_valuenorm or cfg.use_popart:
                    ret_target = value_norm_normalize(value_norm, ret_chunk)
                else:
                    ret_target = ret_chunk
                values, logp, ent = self.policy.evaluate_actions(
                    params, batch["share_obs"], batch["obs"], batch["actions"], batch["available_actions"]
                )
                active = batch["active_masks"]
                ratio = jnp.exp(logp - batch["log_probs"])
                surr1 = ratio * adv_b
                surr2 = jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param) * adv_b
                surr = jnp.minimum(surr1, surr2).sum(axis=-1, keepdims=True)
                if "is_weights" in batch:
                    # V-trace-style truncated IS: the behavior policy that
                    # collected this block lags the target by `lag` updates;
                    # min(rho, rho_bar) reweights the policy gradient toward
                    # the target policy's state distribution, min(rho, c_bar)
                    # bounds the value-target correction (arXiv:1802.01561)
                    surr = surr * jnp.minimum(batch["is_weights"],
                                              cfg.vtrace_rho_bar)
                if cfg.use_policy_active_masks:
                    policy_loss = -(surr * active).sum() / active_full_sum
                    entropy = (ent * active).sum() / active_full_sum
                else:
                    policy_loss = -surr.sum() / (surr.size * accum)
                    entropy = ent.sum() / (ent.size * accum)

                v_clipped = batch["values"] + jnp.clip(
                    values - batch["values"], -cfg.clip_param, cfg.clip_param
                )
                err_clipped = ret_target - v_clipped
                err_orig = ret_target - values
                if cfg.use_huber_loss:
                    vl_clipped = huber_loss(err_clipped, cfg.huber_delta)
                    vl_orig = huber_loss(err_orig, cfg.huber_delta)
                else:
                    vl_clipped = 0.5 * err_clipped**2
                    vl_orig = 0.5 * err_orig**2
                vl = jnp.maximum(vl_orig, vl_clipped) if cfg.use_clipped_value_loss else vl_orig
                if "is_weights" in batch:
                    vl = vl * jnp.minimum(batch["is_weights"], cfg.vtrace_c_bar)
                if cfg.use_value_active_masks:
                    value_loss = (vl * active).sum() / active_full_sum
                else:
                    value_loss = vl.sum() / (vl.size * accum)

                loss = policy_loss - entropy * cfg.entropy_coef + value_loss * cfg.value_loss_coef
                aux = (value_loss, policy_loss, entropy, ratio.sum() / (ratio.size * accum))
                return loss, aux

            # chunks for gradient accumulation: a leading (accum, chunk_rows)
            # reshape of the already-contiguous minibatch — no gathers
            chunks = jax.tree.map(
                lambda x: x.reshape(accum, mb_size // accum, *x.shape[1:]),
                (batch_mb, adv_mb, ret_b),
            )
            if cfg.update_offload:
                # park the chunk stack in host memory; the scan below streams
                # one chunk at a time back on-device (parallel/offload.py)
                from mat_dcml_tpu.parallel.offload import to_host

                chunks = to_host(chunks)

            def chunk_step(acc, chunk):
                g_acc, aux_acc = acc
                if cfg.update_offload:
                    from mat_dcml_tpu.parallel.offload import to_device

                    chunk = to_device(chunk)
                (_, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(params, chunk)
                acc = (
                    jax.tree.map(jnp.add, g_acc, g),
                    jax.tree.map(jnp.add, aux_acc, aux),
                )
                return acc, None

            zero = (
                jax.tree.map(jnp.zeros_like, params),
                tuple(jnp.zeros(()) for _ in range(4)),
            )
            (grads, aux), _ = jax.lax.scan(chunk_step, zero, chunks)

            gnorm = optax.global_norm(grads)
            probe("train/ppo_update",
                  {"grad_norm": gnorm, "value_loss": aux[0], "policy_loss": aux[1]})
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            pnorm = optax.global_norm(params)
            unorm = optax.global_norm(updates)
            value_loss, policy_loss, entropy, ratio_mean = aux
            metrics = TrainMetrics(
                value_loss, policy_loss, entropy, gnorm, ratio_mean,
                param_norm=pnorm,
                update_ratio=unorm / (pnorm + 1e-12),
                nonfinite_grads=(~jnp.isfinite(gnorm)).astype(jnp.float32),
            )
            return params, opt_state, value_norm, metrics

        def run_epoch(carry, key_e, targets):
            params, opt_state, value_norm = carry
            adv_flat, ret_flat = targets if targets is not None else compute_targets(params, value_norm)
            # Rows past mb_size*num_mini_batch are dropped, as the reference
            # floors (shared_buffer.py:250-261).
            perm = jax.random.permutation(key_e, n_rows)
            keep = mb_size * cfg.num_mini_batch

            if layout == "contiguous":
                # one full-permutation gather per epoch; each minibatch is a
                # contiguous dynamic_slice of the permuted copy — identical
                # minibatch CONTENT to the gather path under the same perm
                data_p = permute_rows((flat, adv_flat, ret_flat), perm[:keep])

                def ppo_update(c, start):
                    params, opt_state, value_norm = c
                    batch_mb, adv_mb, ret_b = slice_rows(data_p, start, mb_size)
                    params, opt_state, value_norm, metrics = apply_minibatch(
                        params, opt_state, value_norm, batch_mb, adv_mb, ret_b
                    )
                    return (params, opt_state, value_norm), metrics

                xs = jnp.arange(cfg.num_mini_batch) * mb_size
            else:
                # ONE gather per minibatch (the old path re-gathered per accum
                # chunk); indices-as-xs keeps peak memory at flat + one
                # minibatch — materializing all permuted minibatches as scan
                # xs would add a full extra copy of the batch to HBM
                def ppo_update(c, mb_idx):
                    params, opt_state, value_norm = c
                    batch_mb = jax.tree.map(lambda x: x[mb_idx], flat)
                    params, opt_state, value_norm, metrics = apply_minibatch(
                        params, opt_state, value_norm,
                        batch_mb, adv_flat[mb_idx], ret_flat[mb_idx],
                    )
                    return (params, opt_state, value_norm), metrics

                xs = perm[:keep].reshape(cfg.num_mini_batch, mb_size)

            (params, opt_state, value_norm), metrics = jax.lax.scan(
                ppo_update, (params, opt_state, value_norm), xs
            )
            return (params, opt_state, value_norm), metrics

        keys = jax.random.split(key, cfg.ppo_epoch)
        targets = None if cfg.recompute_returns_per_epoch else compute_targets(state.params, state.value_norm)
        with named_scope("train/ppo_update"):
            (params, opt_state, value_norm), metrics = jax.lax.scan(
                lambda c, k: run_epoch(c, k, targets),
                (state.params, state.opt_state, state.value_norm),
                keys,
            )

        new_state = TrainState(params, opt_state, value_norm, state.update_step + 1)
        # mean over (epoch, minibatch) — except the NaN guard, which counts
        mean_metrics = jax.tree.map(lambda m: m.mean(), metrics)._replace(
            nonfinite_grads=metrics.nonfinite_grads.sum()
        )
        return new_state, mean_metrics
