"""Preemption-safe training: graceful stop, emergency checkpoints, watchdog.

Chip sessions get preempted (the retired ``scripts/tpu_retry_session*.sh``
probe loops are the fossil record); Podracer (PAPERS.md) makes preemptible-TPU
tolerance an architectural property rather than an ops afterthought.  This
module is the runner-side half of that property:

- :class:`GracefulStopHandler` turns SIGTERM/SIGINT into a *requested* stop
  that the training loop honors at the next dispatch boundary — the only
  point where the donated carry (train state, rollout state, key chain) is
  whole and un-donated.
- :class:`EmergencyCheckpoint` is the blocking full-carry checkpoint taken at
  that boundary: params + optimizer + ValueNorm + rollout/env state + the
  PRNG key position, packed with the :func:`flight_recorder.pack_tree`
  deep-copy pattern (typed keys survive as :class:`PRNGKeyLeaf`), written
  atomically next to the regular orbax steps with a CRC-checked manifest.
  Resuming from it re-enters the loop at exactly the captured boundary, so a
  preempted run is bit-exact with an uninterrupted one (tests/
  test_resilience.py pins this through real SIGTERM).
- :class:`DispatchWatchdog` wraps the fused dispatch launch: device errors
  (and, optionally, per-dispatch deadline overruns) re-place the carry from
  the last pre-launch snapshot and retry with the bounded jittered backoff
  policy ``serving/fleet.py`` uses; exhausted retries surface as
  :class:`DispatchFailedError`, which the runner converts into an emergency
  checkpoint plus a nonzero exit.
- :func:`place_carry` is the elastic-resume seam: a packed carry re-places
  onto *whatever* mesh the relaunch got — train-state leaves under the run's
  resolved PartitionSpecs via the spec-aware ``parallel.sharding
  .place_params`` (replicated when no specs, i.e. fsdp=tp=1), env-batch
  leaves re-sharded over the new ``data`` axis via ``put_sharded_state`` —
  with :class:`ElasticResumeError` when the env batch no longer divides the
  shard count or the specs cannot fit the new topology.

Exit codes: ``EXIT_PREEMPTED`` (75, BSD EX_TEMPFAIL — "try again") tells
``scripts/train_supervisor.py`` the stop was a clean preemption (relaunch
immediately, don't count it as a crash); ``EXIT_WATCHDOG`` (76) marks a run
the watchdog gave up on.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import random
import shutil
import signal
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Dict, Optional

from mat_dcml_tpu.telemetry.flight_recorder import pack_tree, unpack_tree

EXIT_PREEMPTED = 75   # EX_TEMPFAIL: graceful stop, relaunch-and-resume me
EXIT_WATCHDOG = 76    # EX_PROTOCOL: dispatch retries exhausted

EMERGENCY_FORMAT = "mat_dcml_tpu/emergency/v1"
_MANIFEST = "manifest.json"
_STATE = "state.pkl"


def backoff_delay(attempt: int, base_ms: float,
                  rand: Callable[[], float] = random.random) -> float:
    """Bounded jittered exponential backoff, in seconds: the one policy the
    watchdog, fleet retries, checkpoint IO, and the relaunch supervisor all
    share — ``base * 2^(attempt-1) * (0.5 + rand())``, attempt counting
    from 1."""
    return (base_ms / 1e3) * (2 ** (attempt - 1)) * (0.5 + rand())


class PreemptedExit(SystemExit):
    """Raised by the runner after a graceful-stop emergency checkpoint; the
    process exits ``EXIT_PREEMPTED`` so supervisors can tell preemption from
    crash."""

    def __init__(self, code: int = EXIT_PREEMPTED):
        super().__init__(code)


class DispatchFailedError(RuntimeError):
    """The watchdog exhausted its retries on one dispatch."""


class ElasticResumeError(ValueError):
    """A packed carry cannot be placed on the current topology/config (env
    batch not divisible by the new ``data`` shard count, or the checkpoint
    was written by an incompatible algorithm/config)."""


# --------------------------------------------------------------------- carry

def pack_carry(episode: int, train_state, rollout_state, key) -> Dict[str, Any]:
    """Blocking host deep-copy of the full training carry at a dispatch
    boundary.  Must run BEFORE the next dispatch launches: donation
    invalidates these buffers, and on the CPU backend ``device_get`` can
    alias them (pack_tree's copy=True is what makes the snapshot survive)."""
    return {
        "episode": int(episode),
        "train_state": pack_tree(train_state),
        "rollout_state": pack_tree(rollout_state),
        "key": pack_tree(key),
    }


def place_carry(snap: Dict[str, Any], mesh=None, state_specs=None):
    """Rebuild ``(train_state, rollout_state, key)`` from a packed carry and
    place it on ``mesh`` (None = host-local single-process placement).

    The mesh does NOT have to match the one the carry was packed on — not in
    ``data`` extent and not in ``fsdp``/``tp`` extent: the packed carry holds
    full host arrays, train-state leaves re-place under ``state_specs``
    through the one spec-aware seam (``parallel.sharding.place_params``;
    None = replicated, the pre-fsdp behavior), and rollout leaves re-shard
    over the new mesh's ``data`` axis by the same shape contract
    ``global_init_state`` uses (leading env-batch axis on every ndim>=1
    leaf).  A carry packed at fsdp=2 resumes at fsdp=4 (and back) this way.
    Divisibility failures surface as :class:`ElasticResumeError`.
    """
    train_state = unpack_tree(snap["train_state"])
    rollout_state = unpack_tree(snap["rollout_state"])
    key = unpack_tree(snap["key"])
    if mesh is not None:
        from mat_dcml_tpu.parallel.distributed import put_sharded_state
        from mat_dcml_tpu.parallel.sharding import ShardMismatchError, place_params

        try:
            train_state = place_params(train_state, mesh, state_specs)
        except (ValueError, ShardMismatchError) as e:
            raise ElasticResumeError(
                f"cannot re-place the checkpointed train state on this mesh: {e}"
            ) from e
        key = place_params(key, mesh)
        try:
            rollout_state = put_sharded_state(rollout_state, mesh)
        except ValueError as e:
            raise ElasticResumeError(
                f"cannot re-place the checkpointed rollout state on this mesh: {e}"
            ) from e
    return train_state, rollout_state, key


# ------------------------------------------------------------- graceful stop

class GracefulStopHandler:
    """SIGTERM/SIGINT -> a stop *request* the loop polls at boundaries.

    The first signal only sets a flag (plus its arrival time, for the
    ``resilience_stop_latency_s`` gauge); the second restores the previous
    handler so a repeated Ctrl-C / kill still terminates a wedged run the
    default way.  ``install`` is a no-op off the main thread (Python only
    allows signal handlers there) — the loop then simply never sees a stop
    request, which is the correct degradation for embedded/test use.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, log=print):
        self.log = log
        self.stop_requested = False
        self.reason: Optional[str] = None
        self._requested_at: Optional[float] = None
        self._previous: Dict[int, Any] = {}
        self.installed = False

    def install(self) -> bool:
        try:
            for sig in self.SIGNALS:
                self._previous[sig] = signal.signal(sig, self._handle)
        except ValueError:       # not the main thread
            self._previous.clear()
            return False
        self.installed = True
        return True

    def uninstall(self) -> None:
        if not self.installed:
            return
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._previous.clear()
        self.installed = False

    def _handle(self, signum, frame) -> None:
        name = signal.Signals(signum).name
        if self.stop_requested:
            # second signal: stop being graceful
            prev = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            self.log(f"[resilience] second {name}: restoring default handling")
            os.kill(os.getpid(), signum)
            return
        self.stop_requested = True
        self.reason = name
        self._requested_at = time.monotonic()
        self.log(f"[resilience] {name} received: stopping at the next "
                 f"dispatch boundary (emergency checkpoint will be taken)")

    def latency_s(self) -> float:
        """Seconds between the stop request and now (0 when never requested)."""
        if self._requested_at is None:
            return 0.0
        return time.monotonic() - self._requested_at


# ------------------------------------------------------ emergency checkpoint

class EmergencyCheckpoint:
    """One-slot blocking full-carry checkpoint beside the regular steps.

    Layout (``<models>/emergency/``): ``state.pkl`` — the pickled packed
    carry — and ``manifest.json`` with the resume episode plus the payload's
    size and CRC32.  Writes build a temp directory and atomically swap it in,
    so a SIGKILL mid-write can never leave a half emergency checkpoint where
    a resume would find it.  ``load`` verifies the CRC and quarantines a
    corrupt slot instead of crashing the relaunch.
    """

    def __init__(self, directory, telemetry=None, log=print):
        self.directory = Path(directory).absolute()
        self.telemetry = telemetry
        self.log = log
        self.last_saved_episode: Optional[int] = None

    # ------------------------------------------------------------------ save

    def save(self, snap: Dict[str, Any], reason: str,
             meta: Optional[Dict[str, Any]] = None) -> Path:
        payload = pickle.dumps(snap, protocol=pickle.HIGHEST_PROTOCOL)
        manifest = {
            "format": EMERGENCY_FORMAT,
            "reason": str(reason),
            "episode": int(snap["episode"]),
            # the episode the resumed loop starts AT: the carry is the input
            # to the dispatch that begins at `episode`
            "next_episode": int(snap["episode"]),
            "state_bytes": len(payload),
            "state_crc32": zlib.crc32(payload),
            "wall_time": time.time(),
        }
        if meta:
            manifest.update(meta)
        tmp = self.directory.parent / f".{self.directory.name}.tmp.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        tmp.mkdir(parents=True)
        (tmp / _STATE).write_bytes(payload)
        (tmp / _MANIFEST).write_text(json.dumps(manifest, indent=1))
        # swap: move the old slot aside, rename the new one in, drop the old.
        # Each rename is atomic, so every observable intermediate state is
        # either the old complete slot, no slot, or the new complete slot.
        old = self.directory.parent / f".{self.directory.name}.old.{os.getpid()}"
        shutil.rmtree(old, ignore_errors=True)
        if self.directory.exists():
            os.rename(self.directory, old)
        os.rename(tmp, self.directory)
        shutil.rmtree(old, ignore_errors=True)
        self.last_saved_episode = int(snap["episode"])
        if self.telemetry is not None:
            self.telemetry.count("resilience_emergency_saves")
        self.log(f"[resilience] emergency checkpoint ({reason}) -> "
                 f"{self.directory} (resume at episode {manifest['next_episode']})")
        return self.directory

    # ------------------------------------------------------------------ load

    def load(self) -> Optional[Dict[str, Any]]:
        """``{"snap": ..., "manifest": ...}`` or None (absent OR corrupt —
        a corrupt slot is quarantined and reported, never fatal)."""
        mpath = self.directory / _MANIFEST
        spath = self.directory / _STATE
        if not mpath.exists() and not spath.exists():
            return None
        why = None
        try:
            manifest = json.loads(mpath.read_text())
            if manifest.get("format") != EMERGENCY_FORMAT:
                why = f"unrecognized format {manifest.get('format')!r}"
            else:
                payload = spath.read_bytes()
                if len(payload) != manifest["state_bytes"]:
                    why = (f"truncated payload ({len(payload)} bytes, manifest "
                           f"says {manifest['state_bytes']})")
                elif zlib.crc32(payload) != manifest["state_crc32"]:
                    why = "payload CRC mismatch"
                else:
                    snap = pickle.loads(payload)
        except Exception as e:
            why = f"unreadable: {e!r}"
        if why is not None:
            self._quarantine(why)
            return None
        return {"snap": snap, "manifest": manifest}

    def _quarantine(self, why: str) -> None:
        dest = self.directory.parent / (
            f"{self.directory.name}.quarantined.{int(time.time())}"
        )
        try:
            os.rename(self.directory, dest)
            (dest / "reason.txt").write_text(why + "\n")
        except OSError:
            pass
        if self.telemetry is not None:
            self.telemetry.count("resilience_quarantined_steps")
        self.log(f"[resilience] emergency checkpoint corrupt ({why}); "
                 f"quarantined -> {dest}")


# ------------------------------------------------------------------ watchdog

@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    # wall-clock bound on one dispatch, enforced by blocking on its outputs
    # (trading the async dispatch overlap for a deadline); 0 disables
    deadline_s: float = 0.0
    # retries per dispatch before DispatchFailedError
    max_retries: int = 2
    # fleet.py backoff: base * 2^(attempt-1) * (0.5 + U())
    backoff_base_ms: float = 100.0
    # pre-launch carry snapshot cadence (dispatches); 0 disables snapshots —
    # graceful stop still works (it packs boundary state directly), but the
    # crash paths (retry, emergency-on-exception) have nothing to restore
    snapshot_interval: int = 1


class DispatchDeadlineError(RuntimeError):
    """One dispatch overran ``deadline_s`` (hung device / degraded chip)."""


class DispatchWatchdog:
    """Deadline + device-error trap around the fused dispatch launch.

    ``arm`` packs the dispatch inputs (blocking device->host deep copy) at
    the configured cadence, BEFORE launch — donation invalidates them right
    after.  ``run`` launches through the trap: a raising dispatch (or one
    overrunning the deadline) is retried from a re-placed copy of that
    snapshot with fleet-style jittered backoff; once retries are exhausted it
    raises :class:`DispatchFailedError`, leaving the snapshot available for
    the runner's emergency-checkpoint path.
    """

    def __init__(self, cfg: WatchdogConfig, mesh=None, telemetry=None,
                 log=print, sleep=time.sleep, rand=random.random):
        self.cfg = cfg
        self.mesh = mesh
        self.telemetry = telemetry
        self.log = log
        self._sleep = sleep
        self._rand = rand
        self._snap: Optional[Dict[str, Any]] = None
        self._snap_is_current = False
        self._calls = 0
        # rule-resolved TrainState PartitionSpecs; the runner's setup()
        # assigns them once resolved so retry re-placement keeps fsdp/tp
        # shardings (None = replicated)
        self.state_specs = None

    @property
    def last_snapshot(self) -> Optional[Dict[str, Any]]:
        return self._snap

    def arm(self, episode: int, train_state, rollout_state, key) -> bool:
        """Snapshot the carry about to be dispatched (cadenced).  Returns
        True when a snapshot was taken this call."""
        if self.cfg.snapshot_interval <= 0:
            return False
        import jax

        if jax.process_count() > 1:
            # cross-process sharded leaves are not fully addressable here;
            # multi-host crash recovery rides the regular orbax steps
            return False
        take = self._calls % self.cfg.snapshot_interval == 0
        self._calls += 1
        self._snap_is_current = take
        if not take:
            return False
        self._snap = pack_carry(episode, train_state, rollout_state, key)
        if self.telemetry is not None:
            self.telemetry.count("resilience_snapshots")
        return True

    def _count(self, name: str) -> None:
        if self.telemetry is not None:
            self.telemetry.count(name)

    def run(self, fn: Callable, train_state, rollout_state, key):
        """Launch ``fn(train_state, rollout_state, key)`` under the trap and
        return its output.  With a deadline configured the call blocks on the
        outputs to time it; without one, errors surface here anyway because
        jax raises on the enqueueing call once the failed buffers are used."""
        import jax

        attempt = 0
        while True:
            started = time.perf_counter()
            try:
                out = fn(train_state, rollout_state, key)
                if self.cfg.deadline_s > 0:
                    jax.block_until_ready(out)
                    elapsed = time.perf_counter() - started
                    if elapsed > self.cfg.deadline_s:
                        raise DispatchDeadlineError(
                            f"dispatch took {elapsed:.2f}s "
                            f"(deadline {self.cfg.deadline_s:.2f}s)"
                        )
                return out
            except DispatchDeadlineError as e:
                self._count("resilience_deadline_overruns")
                err = e
            except Exception as e:
                err = e
            # ---- failure path: re-place from the snapshot and retry
            if self._snap is None or not self._snap_is_current:
                # nothing valid to replay this dispatch from (snapshots off
                # or cadenced past it) — escalate straight to the runner
                self._count("resilience_dispatch_failures")
                raise DispatchFailedError(
                    f"dispatch failed with no replayable snapshot: {err!r}"
                ) from err
            attempt += 1
            if attempt > self.cfg.max_retries:
                self._count("resilience_dispatch_failures")
                raise DispatchFailedError(
                    f"dispatch failed {attempt} times (last: {err!r})"
                ) from err
            self._count("resilience_dispatch_retries")
            delay = backoff_delay(attempt, self.cfg.backoff_base_ms,
                                  rand=self._rand)
            self.log(f"[resilience] dispatch attempt {attempt} failed "
                     f"({err!r}); retrying from the episode "
                     f"{self._snap['episode']} snapshot in {delay * 1e3:.0f}ms")
            self._sleep(delay)
            train_state, rollout_state, key = place_carry(
                self._snap, self.mesh, state_specs=self.state_specs
            )
