"""On-device trajectory collection.

Replaces the reference's rollout machinery — ``dcml_runner.collect/insert``
(``dcml_runner.py:145-288``) plus the subprocess vec-env round trip
(``env_wrappers.py:343-403``) — with one ``lax.scan`` over the episode chunk:
policy decode and env step fused in a single compiled program, envs vectorized
by ``vmap`` instead of OS processes.

The buffer (``shared_buffer.py``) collapses to the stacked scan outputs: a
``Trajectory`` pytree of ``(T, E, A, d)`` arrays.  ``insert``'s mask semantics
(``dcml_runner.py:261-272``) are reproduced: ``masks[t+1] = 1 - done_env[t]``;
``active_masks`` handling keeps the same shape contract (all-ones in DCML since
every agent shares the episode done flag).

Sharding contract (``--data_shards``): every :class:`RolloutState` leaf with a
leading env-batch axis E shards over the mesh ``data`` axis; scalar leaves and
the typed PRNG key stay replicated.  ``parallel.distributed.global_init_state``
derives the placement from exactly this shape contract (ndim >= 1 => sharded),
so new carry fields keep a leading E axis or are scalars — a per-env field
hidden in a scalar-shaped leaf would silently replicate.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.policy import TransformerPolicy


class Trajectory(NamedTuple):
    """Stacked rollout chunk; time-major ``(T, E, A, d)``."""

    share_obs: jax.Array         # (T, E, A, sob)
    obs: jax.Array               # (T, E, A, obs)
    available_actions: jax.Array  # (T, E, A, act_dim)
    actions: jax.Array           # (T, E, A, act_out)
    log_probs: jax.Array         # (T, E, A, act_prob)
    values: jax.Array            # (T, E, A, n_obj)
    rewards: jax.Array           # (T, E, A, n_obj); n_obj=1 unless MO-MAT
    masks: jax.Array             # (T+1, E, A, 1); masks[t+1] = 1 - done_env[t]
    active_masks: jax.Array      # (T+1, E, A, 1)
    delays: jax.Array            # (T, E) env info
    payments: jax.Array          # (T, E)
    dones: jax.Array             # (T, E) episode-end flags
    # DMO-MAT per-step preference weights (T, E, n_obj), resampled at episode
    # boundaries (``dmo_shared_buffer.py:69`` objective_coefficients); None for
    # single-objective and static-weight MO-MAT.
    objective_coefficients: Optional[jax.Array] = None
    # On-device episode accounting over this chunk (device->host transfer is a
    # handful of scalars instead of the (T, E, A) reward/done tensors — which
    # matters on tunneled backends): dict with n_done, done_reward_sum,
    # done_delay_sum, done_payment_sum, step_reward_mean, and per-objective
    # step means.  None when the collector predates the carry (hand-built
    # states).
    chunk_stats: Optional[dict] = None
    # Raw V-trace-style truncated-IS ratios (T, E, A, 1) attached by the
    # async off-policy correction (training/off_policy.py) when the block
    # was collected under stale params (--staleness_budget > 1); the PPO
    # loss clips them at vtrace_rho_bar / vtrace_c_bar.  None everywhere
    # else — collectors never fill this.
    is_weights: Optional[jax.Array] = None


class RolloutState(NamedTuple):
    """Carry between rollout chunks (the reference's ``after_update`` copy of
    the last timestep, ``shared_buffer.py:188-198``)."""

    env_states: NamedTuple       # vmapped env state pytree
    obs: jax.Array               # (E, A, obs)
    share_obs: jax.Array         # (E, A, sob)
    available_actions: jax.Array  # (E, A, act_dim)
    mask: jax.Array              # (E, A, 1) mask entering the next chunk
    rng: jax.Array
    objective_coefficients: Optional[jax.Array] = None  # (E, n_obj), DMO only
    # per-env running episode sums (reward, delay, payment), carried across
    # chunks so episodes spanning chunk boundaries account correctly
    # (dcml_runner.py:29-74 host accounting moved on-device)
    episode_acc: Optional[jax.Array] = None             # (E, 3)


class RolloutCollector:
    """Builds the jittable ``collect`` function for a (policy, env) pair."""

    # explicit fused-dispatch eligibility (base_runner gates on this;
    # host-driven collectors declare False, host_rollout.py:45)
    jittable = True

    def __init__(
        self,
        env,
        policy: TransformerPolicy,
        episode_length: int,
        dynamic_coefficients: bool = False,
    ):
        self.env = env
        self.policy = policy
        self.T = episode_length
        # derived from the policy so reward channels can never silently
        # mismatch the critic's value channels (cfg-less policies, e.g. the
        # random baseline, are single-objective)
        self.n_objective = getattr(getattr(policy, "cfg", None), "n_objective", 1)
        # DMO-MAT: per-env preference weights on the objective simplex,
        # resampled whenever the env episode ends (reconstructing the missing
        # ``dmomat`` runner around ``dmo_shared_buffer.py:69``).  The weights
        # condition the policy — they are appended to share_obs — so the
        # network can actually learn preference-dependent behavior; the policy
        # must be built with state_dim = env.share_obs_dim + n_objective.
        self.dynamic_coefficients = dynamic_coefficients and self.n_objective > 1

    def _sample_coefficients(self, key: jax.Array, n_envs: int) -> jax.Array:
        # Dirichlet(1,...,1) == normalized iid exponentials.  Closed form
        # instead of jax.random.dirichlet because dirichlet samples gamma,
        # a rejection sampler whose while_loop serializes inside the collect
        # scan on TPU (this resamples every step in DMO mode, applied only
        # at episode boundaries).
        e = jax.random.exponential(key, (n_envs, self.n_objective))
        return e / e.sum(axis=-1, keepdims=True)

    def augment_share_obs(self, x: jax.Array, coefs: Optional[jax.Array]) -> jax.Array:
        """Append per-env preference weights to every agent's obs/share_obs row.

        Both views are widened because the MAT encoder reads ``obs`` unless
        ``encode_state`` is set (``ma_transformer.py:144-149``) — augmenting
        share_obs alone would leave the network blind to the preference.
        """
        if not self.dynamic_coefficients:
            return x
        A = x.shape[-2]
        tiled = jnp.broadcast_to(coefs[..., None, :], (*coefs.shape[:-1], A, coefs.shape[-1]))
        return jnp.concatenate([x, tiled], axis=-1)

    def init_state(self, key: jax.Array, n_envs: int) -> RolloutState:
        key, k_reset, k_coef = jax.random.split(key, 3)
        keys = jax.random.split(k_reset, n_envs)
        env_states, ts = jax.vmap(self.env.reset)(keys, jnp.zeros(n_envs, jnp.int32))
        E, A = ts.obs.shape[0], ts.obs.shape[1]
        coefs = self._sample_coefficients(k_coef, E) if self.dynamic_coefficients else None
        return RolloutState(
            env_states=env_states,
            obs=self.augment_share_obs(ts.obs, coefs),
            share_obs=self.augment_share_obs(ts.share_obs, coefs),
            available_actions=ts.available_actions,
            mask=jnp.ones((E, A, 1), jnp.float32),
            rng=key,
            objective_coefficients=coefs,
            episode_acc=jnp.zeros((E, 3), jnp.float32),
        )

    def collect(self, params, rollout_state: RolloutState) -> Tuple[RolloutState, Trajectory]:
        """Roll ``T`` steps; pure function of (params, rollout_state)."""

        use_spec = getattr(self.policy, "decode_mode", "scan") == "spec"

        def body(carry, _):
            st = carry
            key, k_act = jax.random.split(st.rng)
            if use_spec:
                out, spec = self.policy.get_actions_with_stats(
                    params, k_act, st.share_obs, st.obs, st.available_actions,
                    deterministic=False,
                )
            else:
                spec = None
                out = self.policy.get_actions(
                    params, k_act, st.share_obs, st.obs, st.available_actions,
                    deterministic=False,
                )
            env_states, ts = jax.vmap(self.env.step)(st.env_states, out.action)
            done_env = ts.done.all(axis=1)                      # (E,)
            # strongly-typed float32: a weak-typed mask in the carry would give
            # the next chunk's input a different jit signature than init_state's
            # jnp.ones mask — one silent recompile per run (telemetry catches it)
            next_mask = jnp.where(
                done_env[:, None, None], jnp.float32(0.0), jnp.float32(1.0)
            )
            next_mask = jnp.broadcast_to(next_mask, st.mask.shape)
            reward = ts.objectives if self.n_objective > 1 else ts.reward

            # on-device episode accounting: accumulate per-env sums, flush the
            # finished episodes' totals into the chunk aggregates
            step_vals = jnp.stack(
                [reward.sum(-1).mean(-1), ts.delay, ts.payment], axis=-1
            )                                                    # (E, 3)
            acc = st.episode_acc + step_vals
            flushed = jnp.where(done_env[:, None], acc, 0.0).sum(axis=0)   # (3,)
            n_done = done_env.sum().astype(jnp.float32)
            acc = jnp.where(done_env[:, None], 0.0, acc)

            transition = dict(
                share_obs=st.share_obs,
                obs=st.obs,
                available_actions=st.available_actions,
                actions=out.action,
                log_probs=out.log_prob,
                values=out.value,
                rewards=reward,
                next_mask=next_mask,
                delay=ts.delay,
                payment=ts.payment,
                done=done_env,
                _flushed=flushed,
                _n_done=n_done,
            )
            if use_spec:
                # per-step speculative-decode aggregates: mean passes over the
                # env batch, summed draft counters (ratio taken host-side)
                transition["_spec"] = jnp.stack([
                    spec.draft_passes.mean(),
                    spec.verify_passes.mean(),
                    spec.drafts_offered.sum(),
                    spec.drafts_accepted.sum(),
                ])
            if self.dynamic_coefficients:
                # the weights in effect for THIS step; resample where the
                # episode just ended so the next episode gets a fresh preference
                key, k_coef = jax.random.split(key)
                transition["objective_coefficients"] = st.objective_coefficients
                fresh = self._sample_coefficients(k_coef, done_env.shape[0])
                next_coefs = jnp.where(done_env[:, None], fresh, st.objective_coefficients)
            else:
                next_coefs = st.objective_coefficients
            new_st = RolloutState(
                env_states=env_states,
                obs=self.augment_share_obs(ts.obs, next_coefs),
                share_obs=self.augment_share_obs(ts.share_obs, next_coefs),
                available_actions=ts.available_actions,
                mask=next_mask,
                rng=key,
                objective_coefficients=next_coefs,
                episode_acc=acc,
            )
            return new_st, transition

        if rollout_state.episode_acc is None:      # hand-built legacy state
            rollout_state = rollout_state._replace(
                episode_acc=jnp.zeros((rollout_state.obs.shape[0], 3), jnp.float32)
            )
        final_state, tr = jax.lax.scan(body, rollout_state, None, length=self.T)

        flushed = tr.pop("_flushed").sum(axis=0)            # (3,)
        n_done = tr.pop("_n_done").sum()
        chunk_stats = {
            "n_done": n_done,
            "done_reward_sum": flushed[0],
            "done_delay_sum": flushed[1],
            "done_payment_sum": flushed[2],
            "step_reward_mean": tr["rewards"].sum(-1).mean(),
        }
        if self.n_objective > 1:
            for i in range(self.n_objective):
                chunk_stats[f"step_objective_{i}_mean"] = tr["rewards"][..., i].mean()
        if use_spec:
            sp = tr.pop("_spec")                            # (T, 4)
            chunk_stats["spec_draft_passes"] = sp[:, 0].mean()
            chunk_stats["spec_verify_passes"] = sp[:, 1].mean()
            chunk_stats["spec_drafts_offered"] = sp[:, 2].sum()
            chunk_stats["spec_drafts_accepted"] = sp[:, 3].sum()

        masks = jnp.concatenate([rollout_state.mask[None], tr["next_mask"]], axis=0)
        active = jnp.ones_like(masks)
        traj = Trajectory(
            share_obs=tr["share_obs"],
            obs=tr["obs"],
            available_actions=tr["available_actions"],
            actions=tr["actions"],
            log_probs=tr["log_probs"],
            values=tr["values"],
            rewards=tr["rewards"],
            masks=masks,
            active_masks=active,
            delays=tr["delay"],
            payments=tr["payment"],
            dones=tr["done"],
            objective_coefficients=tr.get("objective_coefficients"),
            chunk_stats=chunk_stats,
        )
        return final_state, traj
