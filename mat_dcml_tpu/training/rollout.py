"""On-device trajectory collection.

Replaces the reference's rollout machinery — ``dcml_runner.collect/insert``
(``dcml_runner.py:145-288``) plus the subprocess vec-env round trip
(``env_wrappers.py:343-403``) — with one ``lax.scan`` over the episode chunk:
policy decode and env step fused in a single compiled program, envs vectorized
by ``vmap`` instead of OS processes.

The buffer (``shared_buffer.py``) collapses to the stacked scan outputs: a
``Trajectory`` pytree of ``(T, E, A, d)`` arrays.  ``insert``'s mask semantics
(``dcml_runner.py:261-272``) are reproduced: ``masks[t+1] = 1 - done_env[t]``;
``active_masks`` handling keeps the same shape contract (all-ones in DCML since
every agent shares the episode done flag).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.policy import TransformerPolicy


class Trajectory(NamedTuple):
    """Stacked rollout chunk; time-major ``(T, E, A, d)``."""

    share_obs: jax.Array         # (T, E, A, sob)
    obs: jax.Array               # (T, E, A, obs)
    available_actions: jax.Array  # (T, E, A, act_dim)
    actions: jax.Array           # (T, E, A, act_out)
    log_probs: jax.Array         # (T, E, A, act_prob)
    values: jax.Array            # (T, E, A, n_obj)
    rewards: jax.Array           # (T, E, A, 1)
    masks: jax.Array             # (T+1, E, A, 1); masks[t+1] = 1 - done_env[t]
    active_masks: jax.Array      # (T+1, E, A, 1)
    delays: jax.Array            # (T, E) env info
    payments: jax.Array          # (T, E)
    dones: jax.Array             # (T, E) episode-end flags


class RolloutState(NamedTuple):
    """Carry between rollout chunks (the reference's ``after_update`` copy of
    the last timestep, ``shared_buffer.py:188-198``)."""

    env_states: NamedTuple       # vmapped env state pytree
    obs: jax.Array               # (E, A, obs)
    share_obs: jax.Array         # (E, A, sob)
    available_actions: jax.Array  # (E, A, act_dim)
    mask: jax.Array              # (E, A, 1) mask entering the next chunk
    rng: jax.Array


class RolloutCollector:
    """Builds the jittable ``collect`` function for a (policy, env) pair."""

    def __init__(self, env, policy: TransformerPolicy, episode_length: int):
        self.env = env
        self.policy = policy
        self.T = episode_length

    def init_state(self, key: jax.Array, n_envs: int) -> RolloutState:
        key, k_reset = jax.random.split(key)
        keys = jax.random.split(k_reset, n_envs)
        env_states, ts = jax.vmap(self.env.reset)(keys, jnp.zeros(n_envs, jnp.int32))
        E, A = ts.obs.shape[0], ts.obs.shape[1]
        return RolloutState(
            env_states=env_states,
            obs=ts.obs,
            share_obs=ts.share_obs,
            available_actions=ts.available_actions,
            mask=jnp.ones((E, A, 1), jnp.float32),
            rng=key,
        )

    def collect(self, params, rollout_state: RolloutState) -> Tuple[RolloutState, Trajectory]:
        """Roll ``T`` steps; pure function of (params, rollout_state)."""

        def body(carry, _):
            st = carry
            key, k_act = jax.random.split(st.rng)
            out = self.policy.get_actions(
                params, k_act, st.share_obs, st.obs, st.available_actions, deterministic=False
            )
            env_states, ts = jax.vmap(self.env.step)(st.env_states, out.action)
            done_env = ts.done.all(axis=1)                      # (E,)
            next_mask = jnp.where(done_env[:, None, None], 0.0, 1.0)
            next_mask = jnp.broadcast_to(next_mask, st.mask.shape)
            transition = dict(
                share_obs=st.share_obs,
                obs=st.obs,
                available_actions=st.available_actions,
                actions=out.action,
                log_probs=out.log_prob,
                values=out.value,
                rewards=ts.reward,
                next_mask=next_mask,
                delay=ts.delay,
                payment=ts.payment,
                done=done_env,
            )
            new_st = RolloutState(
                env_states=env_states,
                obs=ts.obs,
                share_obs=ts.share_obs,
                available_actions=ts.available_actions,
                mask=next_mask,
                rng=key,
            )
            return new_st, transition

        final_state, tr = jax.lax.scan(body, rollout_state, None, length=self.T)

        masks = jnp.concatenate([rollout_state.mask[None], tr["next_mask"]], axis=0)
        active = jnp.ones_like(masks)
        traj = Trajectory(
            share_obs=tr["share_obs"],
            obs=tr["obs"],
            available_actions=tr["available_actions"],
            actions=tr["actions"],
            log_probs=tr["log_probs"],
            values=tr["values"],
            rewards=tr["rewards"],
            masks=masks,
            active_masks=active,
            delays=tr["delay"],
            payments=tr["payment"],
            dones=tr["done"],
        )
        return final_state, traj
