"""Multi-agent MuJoCo runner with agent-fault robustness evaluation.

``runner/shared/mujoco_runner.py``: the generic collect/train loop over a
factorized robot, plus fault injection — a chosen agent's torques zeroed
during training (``faulty_action :13-20``) and an eval sweep over faulty
nodes (``train_mujoco.py:68-69``) for few-shot robustness studies.  Fault
masking lives in :class:`FaultyAgentWrapper` so it compiles into the step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.mamujoco import FaultyAgentWrapper
from mat_dcml_tpu.training.base_runner import BaseRunner
from mat_dcml_tpu.training.generic_runner import GenericRunner, build_discrete_policy
from mat_dcml_tpu.training.host_rollout import HostRolloutCollector
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig


class MujocoRunner(GenericRunner):
    """GenericRunner + train-time fault injection + faulty-node eval sweep."""

    def __init__(self, run: RunConfig, ppo: PPOConfig, env,
                 faulty_node: int = -1, random_order: bool = False,
                 log_fn=print):
        self.base_env = env
        self.random_order = random_order
        super().__init__(run, ppo, self._compose(env, faulty_node), log_fn=log_fn)

    def _compose(self, env, faulty_node: int):
        """Fault masking binds to the PHYSICAL agent index, so the fault
        wrapper sits inside and the per-episode permutation outside —
        the permutation un-permutes actions back to physical order before
        the fault zeroes its node (random_mujoco_multi keeps the same
        orientation: permutation at the env boundary)."""
        if faulty_node >= 0:
            env = FaultyAgentWrapper(env, faulty_node)
        if self.random_order:
            from mat_dcml_tpu.envs.permute import AgentPermutationWrapper
            env = AgentPermutationWrapper(env)
        return env

    def evaluate(self, train_state, n_steps: int = 200, seed: int = 0,
                 faulty_node: int = -1):
        """Deterministic mean step reward with ``faulty_node``'s actions
        zeroed (-1 = healthy)."""
        env = self._compose(self.base_env, faulty_node)
        E = self.run_cfg.n_rollout_threads
        rs = self.collector.init_state(jax.random.key(seed + 23), E)

        @jax.jit
        def eval_step(params, st):
            out = self.policy.get_actions(
                params, jax.random.key(0), st.share_obs, st.obs,
                st.available_actions, deterministic=True,
            )
            env_states, ts = jax.vmap(env.step)(st.env_states, out.action)
            new_st = st._replace(
                env_states=env_states, obs=ts.obs, share_obs=ts.share_obs,
                available_actions=ts.available_actions,
            )
            return new_st, ts.reward.mean()

        rewards = []
        for _ in range(n_steps):
            rs, r = eval_step(train_state.params, rs)
            rewards.append(float(r))
        return {"eval_average_step_rewards": float(np.mean(rewards)),
                "faulty_node": faulty_node}

    def evaluate_faulty_sweep(self, train_state,
                              nodes: Sequence[int], n_steps: int = 200,
                              seed: int = 0) -> dict:
        """Robustness sweep over faulty nodes (``train_mujoco.py:68-69``)."""
        return {
            f"eval_reward_faulty_{n}": self.evaluate(
                train_state, n_steps=n_steps, seed=seed, faulty_node=n
            )["eval_average_step_rewards"]
            for n in nodes
        }


class _FaultyVecEnv:
    """Zero one agent's actions at the host-bridge boundary.

    The pure-JAX path compiles :class:`FaultyAgentWrapper` into the env step;
    host workers cannot be re-wrapped after spawn, but the fault semantics
    (``faulty_action:13-20``: the node's torques forced to zero) only touch
    the action tensor — applying them where actions cross to the host is
    equivalent."""

    def __init__(self, inner, node: int):
        self._inner = inner
        self._node = node

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self, actions):
        actions = np.array(actions, copy=True)
        actions[:, self._node] = 0.0
        return self._inner.step(actions)


class MujocoHostRunner(BaseRunner):
    """Real-MuJoCo (gymnasium) training over the host-process bridge.

    The continuous-MAT twin of :class:`FootballRunner`: jitted policy on
    device, :class:`MujocoMultiHostEnv` workers stepping real physics
    (``mujoco_multi.py:39-260`` factorization), fault injection at the
    bridge boundary.

    ``eval_env_fn`` enables evaluation on its own short-lived
    :class:`ShareDummyVecEnv` fleet — the reference keeps eval envs separate
    too (``config.py`` n_eval_rollout_threads), and resetting the TRAINING
    fleet mid-run would desynchronize the collector's held observations from
    worker state.  An index-parameterized factory (``f(i) -> env``) gets
    ``n_envs`` independently-seeded envs; a zero-arg factory gets a fleet of
    one (same-seed duplicates add no variance reduction)."""

    def __init__(self, run: RunConfig, ppo: PPOConfig, vec_env,
                 faulty_node: int = -1, eval_env_fn=None, log_fn=print):
        if run.algorithm_name not in ("mat", "mat_dec"):
            raise NotImplementedError(
                "the MuJoCo host runner drives the MAT family; use "
                "--backend lite for mappo/ippo/happo"
            )
        if run.n_rollout_threads != vec_env.n_envs:
            raise ValueError(
                f"n_rollout_threads={run.n_rollout_threads} != vec env size "
                f"{vec_env.n_envs}"
            )
        self.env = _FaultyVecEnv(vec_env, faulty_node) if faulty_node >= 0 else vec_env
        self.eval_env_fn = eval_env_fn
        self.is_mat = True
        self.policy = build_discrete_policy(run, vec_env)
        self.trainer = MATTrainer(self.policy, ppo, total_updates=run.episodes)
        self.collector = HostRolloutCollector(self.env, self.policy, run.episode_length)

        @jax.jit
        def _det_act(params, key, share, obs, avail):
            return self.policy.get_actions(
                params, key, share, obs, avail, deterministic=True
            )

        self._det_act = _det_act          # compiled once, reused across evals
        if eval_env_fn is None and run.use_eval:
            # BaseRunner's train loop auto-invokes evaluate() when use_eval
            # is set; without a separate eval fleet that would have to reset
            # the training workers — refuse up front instead of corrupting
            raise ValueError(
                "use_eval with the gym backend needs eval_env_fn (a "
                "factory for a separate eval env fleet)"
            )
        self.finalize(run, log_fn)

    def evaluate(self, train_state, n_steps: int = 200, seed: int = 0,
                 faulty_node: int = -1, n_envs: int = 2):
        """Deterministic mean step reward on a FRESH eval fleet."""
        import inspect

        from mat_dcml_tpu.envs.vec_env import ShareDummyVecEnv

        if self.eval_env_fn is None:
            raise ValueError("evaluate() needs eval_env_fn (see class docstring)")
        # an index-parameterized factory gets a distinct index (and thus seed)
        # per eval env; a bare thunk yields a fleet of ONE — n_envs same-seed
        # rollouts would be identical duplicates whose mean adds nothing
        takes_idx = len(inspect.signature(self.eval_env_fn).parameters) >= 1
        if takes_idx:
            fns = [(lambda i=i: self.eval_env_fn(i)) for i in range(n_envs)]
        else:
            fns = [self.eval_env_fn]
        env = ShareDummyVecEnv(fns)
        if faulty_node >= 0:
            env = _FaultyVecEnv(env, faulty_node)
        try:
            obs, share, avail = env.reset()
            rewards = []
            for _ in range(n_steps):
                out = self._det_act(
                    train_state.params, jax.random.key(seed),
                    jnp.asarray(share, jnp.float32), jnp.asarray(obs, jnp.float32),
                    jnp.asarray(avail, jnp.float32),
                )
                obs, share, rew, done, infos, avail = env.step(np.asarray(out.action))
                rewards.append(float(np.mean(rew)))
        finally:
            env.close()
        return {"eval_average_step_rewards": float(np.mean(rewards)),
                "faulty_node": faulty_node}

    evaluate_faulty_sweep = MujocoRunner.evaluate_faulty_sweep
