"""Multi-agent MuJoCo runner with agent-fault robustness evaluation.

``runner/shared/mujoco_runner.py``: the generic collect/train loop over a
factorized robot, plus fault injection — a chosen agent's torques zeroed
during training (``faulty_action :13-20``) and an eval sweep over faulty
nodes (``train_mujoco.py:68-69``) for few-shot robustness studies.  Fault
masking lives in :class:`FaultyAgentWrapper` so it compiles into the step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.mamujoco import FaultyAgentWrapper
from mat_dcml_tpu.training.generic_runner import GenericRunner
from mat_dcml_tpu.training.ppo import PPOConfig


class MujocoRunner(GenericRunner):
    """GenericRunner + train-time fault injection + faulty-node eval sweep."""

    def __init__(self, run: RunConfig, ppo: PPOConfig, env,
                 faulty_node: int = -1, random_order: bool = False,
                 log_fn=print):
        self.base_env = env
        self.random_order = random_order
        super().__init__(run, ppo, self._compose(env, faulty_node), log_fn=log_fn)

    def _compose(self, env, faulty_node: int):
        """Fault masking binds to the PHYSICAL agent index, so the fault
        wrapper sits inside and the per-episode permutation outside —
        the permutation un-permutes actions back to physical order before
        the fault zeroes its node (random_mujoco_multi keeps the same
        orientation: permutation at the env boundary)."""
        if faulty_node >= 0:
            env = FaultyAgentWrapper(env, faulty_node)
        if self.random_order:
            from mat_dcml_tpu.envs.permute import AgentPermutationWrapper
            env = AgentPermutationWrapper(env)
        return env

    def evaluate(self, train_state, n_steps: int = 200, seed: int = 0,
                 faulty_node: int = -1):
        """Deterministic mean step reward with ``faulty_node``'s actions
        zeroed (-1 = healthy)."""
        env = self._compose(self.base_env, faulty_node)
        E = self.run_cfg.n_rollout_threads
        rs = self.collector.init_state(jax.random.key(seed + 23), E)

        @jax.jit
        def eval_step(params, st):
            out = self.policy.get_actions(
                params, jax.random.key(0), st.share_obs, st.obs,
                st.available_actions, deterministic=True,
            )
            env_states, ts = jax.vmap(env.step)(st.env_states, out.action)
            new_st = st._replace(
                env_states=env_states, obs=ts.obs, share_obs=ts.share_obs,
                available_actions=ts.available_actions,
            )
            return new_st, ts.reward.mean()

        rewards = []
        for _ in range(n_steps):
            rs, r = eval_step(train_state.params, rs)
            rewards.append(float(r))
        return {"eval_average_step_rewards": float(np.mean(rewards)),
                "faulty_node": faulty_node}

    def evaluate_faulty_sweep(self, train_state,
                              nodes: Sequence[int], n_steps: int = 200,
                              seed: int = 0) -> dict:
        """Robustness sweep over faulty nodes (``train_mujoco.py:68-69``)."""
        return {
            f"eval_reward_faulty_{n}": self.evaluate(
                train_state, n_steps=n_steps, seed=seed, faulty_node=n
            )["eval_average_step_rewards"]
            for n in nodes
        }
