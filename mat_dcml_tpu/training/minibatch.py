"""Shared minibatch-assembly helpers for the PPO-family trainers.

Two byte-diet knobs live here (Podracer, arXiv:2104.06272 §3: keep the
learner's working set small and streaming):

- ``largest_divisor_leq``: the auto-chunking rule.  Streaming knobs ask for a
  *target* chunk count; the effective count is the largest divisor of the
  row count not above the target, so any shape degrades gracefully to fewer
  chunks (worst case 1 == the monolithic path) instead of tripping a
  divisibility assert.

- ``permute_rows`` / ``slice_rows``: the ``minibatch_layout=contiguous``
  recipe.  One full-permutation gather per epoch up front, then every
  minibatch is a contiguous ``dynamic_slice`` — byte-identical minibatch
  CONTENT to the default per-minibatch gather under the same permutation
  (``permuted[k*mb:(k+1)*mb] == x[perm[k*mb:(k+1)*mb]]``), so the loss
  trajectory matches bitwise (pinned by tests/test_stream_equivalence.py).
  The trade is n_gathers for one gather plus a materialized permuted copy:
  fewer counted gather ops, one full extra batch of peak memory — which is
  why ``gather`` stays the default (BENCHLOG r4 measured the copy's HBM
  cost on chip).
"""

from __future__ import annotations

import jax


MINIBATCH_LAYOUTS = ("gather", "contiguous")


def largest_divisor_leq(n: int, cap: int) -> int:
    """Largest divisor of ``n`` that is ``<= cap`` (>= 1).  ``cap <= 0`` -> 1."""
    if cap <= 0 or n <= 0:
        return 1
    for d in range(min(cap, n), 0, -1):
        if n % d == 0:
            return d
    return 1


def effective_accum(mb_size: int, grad_accum_steps: int, stream_chunks: int) -> int:
    """Effective per-minibatch chunk count.

    An explicit ``grad_accum_steps > 1`` wins (its divisibility is asserted by
    the caller — the user asked for that exact split); otherwise the streaming
    target ``stream_chunks`` is rounded down to the largest divisor of
    ``mb_size`` so the split always exists.  0/1 for both -> monolithic.
    """
    if grad_accum_steps > 1:
        return grad_accum_steps
    return largest_divisor_leq(mb_size, stream_chunks)


def check_layout(layout: str) -> str:
    if layout not in MINIBATCH_LAYOUTS:
        raise ValueError(
            f"minibatch_layout={layout!r} not in {MINIBATCH_LAYOUTS}"
        )
    return layout


def permute_rows(tree, perm):
    """One full-permutation gather over every leaf's leading row axis."""
    return jax.tree.map(lambda x: x[perm], tree)


def slice_rows(tree, start, size: int):
    """Contiguous ``dynamic_slice`` of ``size`` rows at (traced) ``start``."""
    return jax.tree.map(
        lambda x: jax.lax.dynamic_slice_in_dim(x, start, size, axis=0), tree
    )
