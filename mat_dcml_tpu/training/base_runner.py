"""Shared rollout-train orchestration (the L6 "base runner" layer).

The JAX counterpart of the reference's ``runner/shared/base_runner.py``: the
collect / insert / compute / train phases collapse into two jitted calls per
episode chunk — ``collect`` (rollout scan) and ``train`` — with host-side code
left for logging, episode accounting, and checkpointing only.  Env-specific
runners (``DCMLRunner``, ``GenericRunner``) build the policy/trainer/collector
in ``__init__`` and call :meth:`finalize`; everything else lives here once.

Restore-at-construction: ``RunConfig.model_dir`` reloads the latest checkpoint
in ``setup`` and continues the episode counter — the reference's
``--model_dir`` restore (``base_runner.py:264-265``) upgraded to full-state
resume (optimizer + ValueNorm included, training/checkpoint.py).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional

import jax
import numpy as np

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.telemetry import (
    AnomalyDetector,
    DeferredFetch,
    FlightRecorder,
    InstrumentedJit,
    ProfilerWindow,
    Telemetry,
    Tracer,
    device_memory_gauges,
    host_rss_bytes,
    instrumented_jit,
    replica_hbm_high_water_bytes,
    set_named_scopes,
)
from mat_dcml_tpu.training.checkpoint import CheckpointManager
from mat_dcml_tpu.training.mappo import Bootstrap
from mat_dcml_tpu.training.ppo import PPOConfig
from mat_dcml_tpu.training.resilience import (
    EXIT_WATCHDOG,
    DispatchFailedError,
    DispatchWatchdog,
    ElasticResumeError,
    EmergencyCheckpoint,
    GracefulStopHandler,
    PreemptedExit,
    WatchdogConfig,
    pack_carry,
    place_carry,
)
from mat_dcml_tpu.utils.metrics import MetricsWriter


def apply_mesh(run: RunConfig, policy):
    """--data_shards / --seq_shards: build the run's global ``(data, seq)``
    mesh (parallel/mesh.build_run_mesh) and attach the ``seq`` ring to the
    policy when the agent axis is context-sharded.  Returns the mesh, or
    ``None`` when the run is unsharded single-process — :meth:`BaseRunner
    .setup` then keeps the classic host-local state construction.

    Called after EVERY policy construction so an unsupported combination
    fails at startup, not silently (or mid-first-update).  Multi-process runs
    always get a mesh over the GLOBAL device set: program state is then built
    through ``parallel.distributed.global_init_state``, which is what retired
    the old ``--seq_shards`` + ``process_count > 1`` NotImplementedError.
    """
    seq = max(1, int(getattr(run, "seq_shards", 1)))
    if seq > 1:
        if not hasattr(policy, "seq_mesh"):
            raise NotImplementedError(
                f"--seq_shards applies to the MAT transformer policy, not "
                f"{type(policy).__name__}"
            )
        if getattr(policy.cfg, "dec_actor", False):
            raise NotImplementedError(
                "--seq_shards: MAT-Dec's per-agent MLPs are indexed by global "
                "agent id; context-sharding applies to the transformer path"
            )
    fsdp = max(1, int(getattr(run, "fsdp_shards", 1)))
    tp = max(1, int(getattr(run, "tp_shards", 1)))
    if getattr(run, "async_actors", False):
        if int(getattr(run, "data_shards", 1)) > 1 or seq > 1 or fsdp > 1 or tp > 1:
            raise ValueError(
                "--async_actors builds its own disjoint actor/learner "
                "submeshes; size them with --actor_devices/--learner_devices, "
                "not --data_shards/--seq_shards/--fsdp_shards/--tp_shards"
            )
        # no run mesh: _train_loop_async builds the submeshes itself (state
        # starts host-local, exactly like the unsharded single-process path)
        return None
    if fsdp > 1 or tp > 1:
        n_embd = int(getattr(getattr(policy, "cfg", None), "n_embd", 0))
        if n_embd and n_embd % (fsdp * tp):
            # the rules layer would catch this per-param at init; catching it
            # here names the flags instead of a flattened param path
            raise ValueError(
                f"--fsdp_shards {fsdp} x --tp_shards {tp} must divide n_embd "
                f"({n_embd}): every column-parallel kernel splits n_embd over "
                f"both param axes"
            )
    from mat_dcml_tpu.parallel.mesh import build_run_mesh

    mesh = build_run_mesh(int(getattr(run, "data_shards", 1)), seq, fsdp, tp)
    if mesh is None:
        return None
    n_data = dict(mesh.shape)["data"]
    if run.n_rollout_threads % n_data:
        raise ValueError(
            f"--n_rollout_threads {run.n_rollout_threads} must be divisible "
            f"by the data shard count ({n_data})"
        )
    if seq > 1:
        policy.seq_mesh = mesh
    if fsdp > 1 or tp > 1:
        # same sharding-invariance hazard as the composed (data x seq) case
        # below: params under P(fsdp, tp) make every sampling site a
        # multi-axis program with replicated inputs — enable partitionable
        # threefry before the first trace so rollout bits match the
        # replicated topology
        jax.config.update("jax_threefry_partitionable", True)
    if seq > 1 and n_data > 1:
        # Composed (data x seq) mesh: jax 0.4.x default threefry is NOT
        # sharding-invariant on a multi-axis mesh with a replicated axis —
        # sampling under P("data") inputs draws different bits than the same
        # program unsharded (reproduced on plain jax.random.categorical), so
        # rollout actions silently diverge across topologies.  Partitionable
        # threefry restores invariance; it changes the raw stream, which is
        # why it is scoped to composed runs only (goldens stay bit-exact on
        # unsharded and data-only topologies).  Must run before the first
        # trace, which apply_mesh — called at runner construction — is.
        jax.config.update("jax_threefry_partitionable", True)
    return mesh


def apply_seq_shards(run: RunConfig, policy) -> None:
    """Back-compat alias: validate + wire sharding flags, discarding the mesh
    (callers that only need ``policy.seq_mesh`` set, e.g. replay/dryrun
    paths).  Runners use :func:`apply_mesh` and keep the return value."""
    apply_mesh(run, policy)


def make_dispatch_fn(trainer, collector, iters: int, state_shardings=None):
    """Build the fused multi-episode dispatch: ONE jittable function that
    ``lax.scan``-s ``iters`` collect+train iterations, so a single host
    dispatch advances ``iters`` episodes (the Podracer anakin pattern).

    Key handling matches the K=1 host loop exactly — one
    ``jax.random.split(key)`` per iteration off the carried key, the evolved
    key returned — so a K-iteration dispatch chain is equivalent to K
    sequential host-loop episodes started from the same key (pinned by
    tests/test_fused_dispatch.py).  Per-iteration train metrics and
    chunk_stats come back stacked ``(iters, ...)``; jit this with
    ``donate_argnums=(0, 1)`` so the carried train/rollout state reuses its
    own buffers instead of being copied every call.

    ``state_shardings`` (a TrainState-shaped tree of NamedShardings, built
    from the rule-resolved specs) pins the carried train state's layout
    inside the scan body.  Without it GSPMD is free to re-shard outputs it
    considers cheap to move (observed: replicated biases coming back
    fsdp-sharded), which breaks the dispatch's steady-state contract — the
    next call's input shardings no longer match the compiled executable, so
    the call either recompiles or (donating) dies.  Param-sharded runners
    MUST pass this; replicated/data-only runs don't need it.
    """

    def dispatch(train_state, rollout_state, key):
        def body(carry, _):
            ts, rs, k = carry
            k, k_train = jax.random.split(k)
            ts, rs, metrics, stats = trainer.train_iteration(collector, ts, rs, k_train)
            if state_shardings is not None:
                ts = jax.lax.with_sharding_constraint(ts, state_shardings)
            return (ts, rs, k), (metrics, stats)

        (train_state, rollout_state, key), stacked = jax.lax.scan(
            body, (train_state, rollout_state, key), None, length=iters
        )
        if state_shardings is not None:
            # pin the ROOT output too: GSPMD propagation may still reshard
            # the loop result on the way out (the body pin alone is not
            # enough when neighboring ops prefer a different layout)
            train_state = jax.lax.with_sharding_constraint(
                train_state, state_shardings)
        return train_state, rollout_state, key, stacked

    return dispatch


def _cadence_hits(interval: int, ep0: int, k: int) -> bool:
    """True when any episode in ``[ep0, ep0 + k)`` lands on the cadence
    (``episode % interval == 0``) — the dispatch-granular version of the K=1
    loop's per-episode checks, so log/save/eval intervals effectively round
    UP to dispatch boundaries."""
    if interval <= 0:
        return False
    return (ep0 + interval - 1) // interval * interval < ep0 + k


def bootstrap_input(is_mat: bool, collector, rs):
    """The trainer's bootstrap argument for a post-collect rollout state:
    MAT-family trainers consume the rollout state directly; the AC family
    takes a :class:`Bootstrap`.  Module-level so ``scripts/replay_bundle.py``
    can mirror the host loop's train call exactly."""
    if is_mat:
        return rs
    use_local = getattr(collector, "use_local_value", False)
    cent = rs.obs if use_local else rs.share_obs
    return Bootstrap(cent_obs=cent, critic_h=rs.critic_h, mask=rs.mask)


def ac_config_kwargs(ppo: PPOConfig) -> dict:
    """PPOConfig -> MAPPOConfig shared-field mapping (one place, so CLI flags
    behave identically across entry points)."""
    return dict(
        lr=ppo.lr, critic_lr=ppo.lr, ppo_epoch=ppo.ppo_epoch,
        num_mini_batch=ppo.num_mini_batch, clip_param=ppo.clip_param,
        entropy_coef=ppo.entropy_coef, value_loss_coef=ppo.value_loss_coef,
        max_grad_norm=ppo.max_grad_norm, gamma=ppo.gamma,
        gae_lambda=ppo.gae_lambda, data_chunk_length=ppo.data_chunk_length,
        minibatch_layout=ppo.minibatch_layout,
    )


class BaseRunner:
    """Collect/train loop with episode metric accounting.

    Subclass contract: ``__init__`` sets ``self.policy``, ``self.trainer``,
    ``self.collector`` and ``self.is_mat`` (True when the trainer consumes the
    rollout state directly — MAT family and the random baseline — False for
    the actor-critic family, which takes a :class:`Bootstrap`), then calls
    ``finalize(run)``.
    """

    run_cfg: RunConfig
    is_mat: bool

    def finalize(self, run: RunConfig, log_fn=print) -> None:
        self.run_cfg = run
        self.log = log_fn
        # runners that shard set self.mesh (= apply_mesh(...)) before calling
        # finalize; everything downstream branches on "is there a mesh"
        self.mesh = getattr(self, "mesh", None)
        # rule-resolved TrainState PartitionSpecs (parallel/sharding.py),
        # filled in by setup(); None until then (and forever at fsdp=tp=1,
        # where every placement site falls back to replicated)
        self.state_specs = None
        self.param_specs = None
        set_named_scopes(run.trace_named_scopes)
        self.telemetry = Telemetry()
        self.telemetry.rate("env_steps", "env_steps_per_sec")
        self.telemetry.rate("agent_steps", "agent_steps_per_sec")
        # tuned-config application record (--tuned_config, applied by
        # config.parse_cli_with_extras before the runner exists): publish the
        # tune_ gauge family so metrics.jsonl shows which knobs this run
        # actually trained with and what the search measured for them
        from mat_dcml_tpu.tuning import last_application

        tuned = last_application()
        if getattr(run, "tuned_config", None) and tuned is not None:
            for name, value in tuned.gauges().items():
                self.telemetry.gauge(name, value)
        # host-loop collectors (vec-env bridge) drive jitted policy calls
        # internally and cannot themselves be traced
        if getattr(self.collector, "jittable", True):
            self._collect = instrumented_jit(
                self.collector.collect, "collect", self.telemetry, log_fn,
                count_collectives=self.mesh is not None,
            )
        else:
            self._collect = self.collector.collect
        # the train step pins its output train-state layout to the rule-
        # resolved shardings (traced AFTER setup() fills state_specs): GSPMD
        # otherwise re-shards cheap outputs (e.g. replicated biases ->
        # fsdp-sharded), drifting the steady-state input signature
        def _train_pinned(ts, *args, **kwargs):
            ts, metrics = self.trainer.train(ts, *args, **kwargs)
            sh = self._state_shardings()
            if sh is not None:
                ts = jax.lax.with_sharding_constraint(ts, sh)
            return ts, metrics

        self._train = instrumented_jit(
            _train_pinned, "train", self.telemetry, log_fn,
            count_collectives=self.mesh is not None,
        )
        # fused multi-episode dispatch (built lazily by _train_loop_fused when
        # --iters_per_dispatch > 1 and the trainer/collector pair supports it)
        self._dispatch = None
        self._dispatch_iters = 1
        # tripwires + capture-at-failure (telemetry/anomaly.py,
        # telemetry/flight_recorder.py): detection feeds off the metrics the
        # loop already fetches; the recorder snapshots dispatch inputs BEFORE
        # launch, the only point where donated buffers are still valid
        self.anomaly = (
            AnomalyDetector(telemetry=self.telemetry,
                            exemplar_fn=self._trace_exemplar)
            if run.anomaly_tripwires else None
        )
        self.profile_window = ProfilerWindow(
            run.anomaly_dir, run.anomaly_profile_dispatches, log_fn
        )
        self.flight = FlightRecorder(
            depth=run.flight_recorder_depth,
            interval=run.flight_recorder_interval,
            directory=run.anomaly_dir,
            run_config=run,
            ppo_config=getattr(self, "ppo_cfg", None),
            env=getattr(self, "env", None) or getattr(self.collector, "env", None),
            telemetry=self.telemetry,
            log=log_fn,
        )
        self.run_dir = (
            Path(run.run_dir) / run.env_name / run.scenario / run.algorithm_name / run.experiment_name
        )
        self.ckpt = CheckpointManager(self.run_dir / "models",
                                      telemetry=self.telemetry, log=log_fn)
        # preemption safety (training/resilience.py): graceful-stop flag,
        # one-slot full-carry emergency checkpoint, dispatch watchdog
        self.stop = (GracefulStopHandler(log=log_fn)
                     if getattr(run, "graceful_stop", True) else None)
        self.emergency = EmergencyCheckpoint(
            self.run_dir / "models" / "emergency",
            telemetry=self.telemetry, log=log_fn,
        )
        self.watchdog = DispatchWatchdog(
            WatchdogConfig(
                deadline_s=float(getattr(run, "dispatch_deadline_s", 0.0)),
                max_retries=int(getattr(run, "dispatch_retries", 2)),
                backoff_base_ms=float(getattr(run, "dispatch_backoff_ms", 100.0)),
                snapshot_interval=int(getattr(run, "emergency_snapshot_interval", 1)),
            ),
            mesh=self.mesh, telemetry=self.telemetry, log=log_fn,
        )
        self._resume_key = None           # PRNG position from an emergency resume
        self._restored_carry = None       # {"rollout_state": ...} ditto
        self._emergency_saved_episode = None
        self._restored_step = -1
        self.metrics_path = self.run_dir / "metrics.jsonl"
        self.writer = MetricsWriter(
            self.run_dir,
            use_tensorboard=run.use_tensorboard,
            use_wandb=run.use_wandb,
            wandb_project=run.wandb_project,
            run_name=f"{run.env_name}/{run.scenario}/{run.algorithm_name}/{run.experiment_name}",
            max_mb=getattr(run, "metrics_max_mb", 0.0),
        )
        # dispatch-granularity span traces (telemetry/tracing.py): the
        # training counterpart of the serving request traces — root
        # "dispatch", children collect/train/fetch/checkpoint — sampled into
        # <run_dir>/trace.jsonl next to metrics.jsonl
        self.tracer = (
            Tracer(self.run_dir, sample=run.trace_sample,
                   max_mb=getattr(run, "trace_max_mb", 64.0))
            if getattr(run, "trace_sample", 0.0) > 0 else None
        )
        # observability federation (telemetry/remote.py): --obs_port exposes
        # this process's registry at /telemetry.json on a daemon sidecar
        # thread, so a supervisor-relaunched trainer is scrapeable by
        # scripts/obs_collector.py alongside the serving fleet
        # (-1 binds an ephemeral port — harness-friendly; the bound port is
        # announced on the OBS_PORT log line either way)
        # bounded trend rollups (telemetry/timeseries.py): every metrics
        # flush is diffed into tiered time windows and closed raw windows
        # stream as typed ts_ records into <run_dir>/timeseries.jsonl —
        # the trend view a 24h soak reads instead of the unbounded
        # metrics.jsonl
        self.rollup = None
        self.ts_writer = None
        if getattr(run, "timeseries", True):
            from mat_dcml_tpu.telemetry.timeseries import RollupStore

            self.rollup = RollupStore()
            self.ts_writer = MetricsWriter(
                self.run_dir, jsonl_name="timeseries.jsonl",
                max_mb=getattr(run, "metrics_max_mb", 0.0) or 16.0)
        self.obs_sidecar = None
        if int(getattr(run, "obs_port", 0) or 0) != 0:
            from mat_dcml_tpu.telemetry.remote import TelemetrySidecar

            self.obs_sidecar = TelemetrySidecar(
                self.telemetry, port=max(0, int(run.obs_port)),
                label="trainer", rollup=self.rollup, log_fn=log_fn)
            self.obs_sidecar.start()
            log_fn(f"OBS_PORT {self.obs_sidecar.port}")
        self._fused_fallback = 0.0
        self.start_episode = 0

    def _trace_exemplar(self):
        """Most recent sampled dispatch trace id (None when tracing is off)
        — pinned on anomaly trips so incidents link to a concrete tree."""
        tracer = getattr(self, "tracer", None)
        return tracer.last_trace_id if tracer is not None else None

    def _rollup_flush(self, record: Optional[dict] = None) -> None:
        """Diff the registry into the rollup store and stream any closed
        windows as ts_ records (called at metrics-flush cadence)."""
        if self.rollup is None:
            return
        self.rollup.observe_telemetry(self.telemetry, source="trainer")
        if record:
            # derived per-interval fields (fps, step_time_* interval means)
            # live only in the flushed record — observed series reset at
            # flush, so they never reach the registry diff above.  Folded
            # gauge-style under their own names: disjoint from every
            # counter/gauge/hist family, so no double-representation.
            derived = {k: v for k, v in record.items()
                       if k == "fps" or k.startswith("step_time")}
            if derived:
                self.rollup.observe_record(derived)
        for rec in self.rollup.drain_records():
            self.ts_writer.write(rec)
        # publish the store's own accounting so the ts_ gauge family rides
        # the next metrics flush (and the scrape plane)
        for name, v in self.rollup.gauges().items():
            self.telemetry.gauge(name, v)

    # ------------------------------------------------------------------ setup

    def _bootstrap(self, rs):
        return bootstrap_input(self.is_mat, self.collector, rs)

    def _state_shardings(self):
        """TrainState-shaped NamedShardings from the rule-resolved specs, or
        None when no param axis is in play (replicated/data-only runs keep
        their seed-identical programs).  Used to pin train-step / fused-
        dispatch output layouts — without the pin GSPMD may re-shard cheap
        outputs and drift the steady-state input signature."""
        if self.state_specs is None or self.mesh is None:
            return None
        from mat_dcml_tpu.parallel.sharding import has_param_axes, named_shardings

        if not has_param_axes(self.mesh):
            return None
        return named_shardings(self.state_specs, self.mesh)

    def setup(self, seed: Optional[int] = None):
        seed = self.run_cfg.seed if seed is None else seed
        key = jax.random.key(seed)
        k_model, k_roll = jax.random.split(key)
        init_p = (self.trainer.init_params if hasattr(self.trainer, "init_params")
                  else self.policy.init_params)  # stacked per-agent vs shared
        if self.mesh is not None:
            # sharded run: build state as GLOBAL arrays, born with their
            # rule-resolved PartitionSpecs (parallel/sharding.py) — params and
            # optimizer moments never exist replicated when fsdp/tp shard
            # them (every process initializes inside jit with out_shardings,
            # so no host-side full-size transfer).  At fsdp=tp=1 the specs
            # resolve to all-P() and this is exactly the old replicated init.
            # The rollout state's env-batch axis shards over "data"; grad
            # psums and batch-statistic reductions fall out of jit.
            from mat_dcml_tpu.parallel.distributed import global_init_state
            from mat_dcml_tpu.parallel.sharding import (
                load_rules, named_shardings, param_byte_stats, resolve_state_specs,
            )

            rules_path = getattr(self.run_cfg, "sharding_rules", None)
            rules = load_rules(rules_path) if rules_path else None
            p_probe = jax.eval_shape(init_p, k_model)
            self.param_specs = resolve_state_specs(p_probe, self.mesh, rules)
            params = jax.jit(
                init_p, out_shardings=named_shardings(self.param_specs, self.mesh)
            )(k_model)
            s_probe = jax.eval_shape(self.trainer.init_state, p_probe)
            self.state_specs = resolve_state_specs(s_probe, self.mesh, rules)
            train_state = jax.jit(
                self.trainer.init_state,
                out_shardings=named_shardings(self.state_specs, self.mesh),
            )(params)
            self.watchdog.state_specs = self.state_specs
            for k, v in param_byte_stats(p_probe, self.param_specs, self.mesh).items():
                self.telemetry.gauge(f"shard_param_{k}", float(v))
            state_stats = param_byte_stats(s_probe, self.state_specs, self.mesh)
            self.telemetry.gauge(
                "shard_param_opt_max_device_bytes", float(state_stats["max_device_bytes"])
            )
        else:
            params = init_p(k_model)
            train_state = self.trainer.init_state(params)
        resume = getattr(self.run_cfg, "resume", "strict")
        restore_dir = self.run_cfg.model_dir or (
            str(self.ckpt.directory) if resume == "auto" else None
        )
        if restore_dir:
            train_state = self._maybe_restore(train_state, directory=restore_dir)
            self.start_episode = self._restored_step + 1
        if self._restored_carry is not None:
            # emergency resume carries the rollout/env state too (placed for
            # this run's mesh in _maybe_restore) — do not re-init it
            rollout_state = self._restored_carry["rollout_state"]
        elif self.mesh is not None:
            rollout_state = global_init_state(
                self.collector, k_roll, self.run_cfg.n_rollout_threads, self.mesh
            )
        else:
            rollout_state = self.collector.init_state(
                k_roll, self.run_cfg.n_rollout_threads
            )
        self._log_model_stats(train_state)
        return train_state, rollout_state

    def _maybe_restore(self, train_state, params_only: bool = False,
                       directory: Optional[str] = None):
        """Restore from ``directory`` (default ``model_dir``).
        ``params_only=True`` = transfer semantics: weights reload, fresh
        optimizer/normalizer/schedule (the reference's restore loads only the
        state_dict, SURVEY §5 checkpoint notes); False = full-state lossless
        resume.

        Sources, newest-progress wins: the latest *valid* regular step
        (damaged steps are quarantined, not fatal —
        ``CheckpointManager.restore_latest_valid``) vs. the emergency
        full-carry checkpoint a graceful stop / crash wrote.  The emergency
        slot also restores the rollout state and PRNG position, making the
        resumed run bit-exact with an uninterrupted one; it may have been
        packed on a different mesh — ``place_carry`` re-shards it for this
        run's topology.  ``resume="auto"`` turns "nothing found" into a
        fresh start instead of FileNotFoundError."""
        directory = Path(directory or self.run_cfg.model_dir).absolute()
        resume = getattr(self.run_cfg, "resume", "strict")
        # reuse self.ckpt when restoring from this run's own models dir — two
        # managers on one directory would hold independent stale step caches
        mgr = (self.ckpt if directory == self.ckpt.directory
               else CheckpointManager(directory, telemetry=self.telemetry,
                                      log=self.log))
        step, restored = mgr.restore_latest_valid(template=train_state)

        found = None if params_only else self._load_emergency(directory)
        next_ep = found["manifest"]["next_episode"] if found else None
        # a regular step S resumes at S+1 with a FRESH rollout state and key;
        # the emergency carry resumes at next_ep with the interrupted run's
        # exact rollout state and PRNG position.  Prefer it on ties (equal
        # progress, strictly more faithful) and whenever it is newer.
        if found is not None and next_ep > (step if step is not None else -1):
            ts, rs, k = self._place_emergency(found["snap"], train_state)
            self._restored_step = next_ep - 1
            self._restored_carry = {"rollout_state": rs}
            self._resume_key = k
            self.log(f"restored emergency checkpoint "
                     f"({found['manifest'].get('reason', '?')}) from "
                     f"{directory / 'emergency'}; resuming at episode {next_ep}")
            return ts

        if restored is None:
            if resume == "auto":
                self.log(f"[resume auto] no checkpoint under {directory}; "
                         f"starting fresh")
                self._restored_step = -1
                return train_state
            raise FileNotFoundError(f"no checkpoint under {directory}")
        self._restored_step = step
        kind = "params" if params_only else "full state"
        self.log(f"restored checkpoint step {step} ({kind}) from {directory}")
        if params_only:
            restored = train_state._replace(params=restored.params)
        if self.mesh is not None:
            # checkpoints restore as host-local arrays; re-place them under
            # this run's resolved specs (replicated when fsdp=tp=1) so
            # donation/sharding layouts match the jit-initialized cold-start
            # state.  A checkpoint saved at fsdp=2 restores onto fsdp=4 (or
            # back) here: the host arrays are full, place_params reshards.
            from mat_dcml_tpu.parallel.sharding import place_params

            restored = place_params(restored, self.mesh, self.state_specs)
        return restored

    def _load_emergency(self, directory: Path):
        emergency = (self.emergency
                     if Path(directory) == self.emergency.directory.parent
                     else EmergencyCheckpoint(Path(directory) / "emergency",
                                              telemetry=self.telemetry,
                                              log=self.log))
        return emergency.load()

    def _place_emergency(self, snap, template):
        """Place a packed emergency carry for this run's topology, with typed
        errors when it cannot fit."""
        try:
            ts, rs, k = place_carry(snap, self.mesh, state_specs=self.state_specs)
        except ElasticResumeError:
            raise
        if (jax.tree.structure(ts) != jax.tree.structure(template)):
            raise ElasticResumeError(
                "emergency checkpoint train-state structure does not match "
                "this run's trainer (different algorithm or model config?)"
            )
        E = self.run_cfg.n_rollout_threads
        leaves = jax.tree.leaves(rs)
        batched = [x for x in leaves if getattr(x, "ndim", 0) >= 1]
        if batched and any(int(x.shape[0]) != E for x in batched):
            got = {int(x.shape[0]) for x in batched}
            raise ElasticResumeError(
                f"emergency checkpoint was taken with n_rollout_threads="
                f"{sorted(got)} but this run uses {E}; elastic resume reshapes "
                f"the mesh, not the env batch"
            )
        return ts, rs, k

    def _log_model_stats(self, train_state) -> None:
        """The reference's parameter-count block + THOP hook, XLA-native
        (utils/profiling.py); one line at startup, like its commented probe."""
        from mat_dcml_tpu.utils.profiling import model_stats_line

        self.log(model_stats_line(train_state.params))

    # ------------------------------------------------------------------ train

    def train_loop(self, num_episodes: Optional[int] = None, train_state=None, rollout_state=None):
        run = self.run_cfg
        episodes = num_episodes if num_episodes is not None else run.episodes
        if train_state is None:
            train_state, rollout_state = self.setup()
        # an emergency resume restores the PRNG position too — continuing the
        # interrupted chain is what makes resume bit-exact with an
        # uninterrupted run
        key = (self._resume_key if self._resume_key is not None
               else jax.random.key(run.seed + 7919))

        if self.stop is not None:
            self.stop.install()
        K = max(1, int(getattr(run, "iters_per_dispatch", 1)))
        use_async = bool(getattr(run, "async_actors", False))
        if use_async and K > 1:
            raise ValueError(
                "--async_actors and --iters_per_dispatch > 1 are alternative "
                "overlap strategies (two submesh programs vs one fused "
                "program); pick one"
            )
        if use_async:
            # same fallback-visibility contract as the fused path: when the
            # overlap cannot run, say so in a gauge, then take the classic loop
            if not getattr(self.collector, "jittable", True):
                use_async = False
                self.telemetry.gauge("async_fallback", 1.0)
                self.log("[async] collector is host-driven (jittable=False); "
                         "--async_actors ignored")
            elif jax.device_count() < 2 or jax.process_count() > 1:
                use_async = False
                self.telemetry.gauge("async_fallback", 1.0)
                self.log(f"[async] needs a single process with >= 2 devices "
                         f"(have {jax.device_count()} devices, "
                         f"{jax.process_count()} processes); --async_actors "
                         f"ignored")
        try:
            if use_async:
                self.telemetry.gauge("async_fallback", 0.0)
                return self._train_loop_async(episodes, train_state, rollout_state, key)
            if K > 1:
                # the fallback gauge makes the silently-taken path visible to
                # metrics.jsonl consumers (BENCHLOG legs, schema checker):
                # 1.0 = fused dispatch was requested but fell back to the
                # classic loop, 0.0 = the fused path actually ran
                if not getattr(self.collector, "jittable", True):
                    self._fused_fallback = 1.0
                    self.telemetry.gauge("dispatch_fused_fallback", 1.0)
                    self.log("[dispatch] collector is host-driven (jittable=False); "
                             "--iters_per_dispatch ignored")
                elif not hasattr(self.trainer, "train_iteration"):
                    self._fused_fallback = 1.0
                    self.telemetry.gauge("dispatch_fused_fallback", 1.0)
                    self.log(f"[dispatch] {type(self.trainer).__name__} has no "
                             f"train_iteration; --iters_per_dispatch ignored")
                else:
                    self.telemetry.gauge("dispatch_fused_fallback", 0.0)
                    return self._train_loop_fused(episodes, train_state, rollout_state, key, K)
            return self._train_loop_episodic(episodes, train_state, rollout_state, key)
        except PreemptedExit:
            raise                      # already emergency-checkpointed
        except DispatchFailedError as e:
            self._emergency_on_failure(repr(e))
            self.log(f"[resilience] dispatch retries exhausted: {e}")
            raise SystemExit(EXIT_WATCHDOG) from e
        except BaseException as e:
            # unhandled crash: save what the watchdog last snapshotted so the
            # relaunch loses at most emergency_snapshot_interval dispatches
            self._emergency_on_failure(repr(e))
            raise
        finally:
            if self.stop is not None:
                self.stop.uninstall()
            # a tripwire profiler window still open at exit — normal return OR
            # a crash mid-run — must stop its trace or the xplane.pb is corrupt
            self.profile_window.close()
            if self.obs_sidecar is not None:
                self.obs_sidecar.stop()
            # final rollup flush: the still-open raw window never closed, but
            # the diff state must land so the last interval is not lost
            self._rollup_flush()
            if self.ts_writer is not None:
                self.ts_writer.close()
            if self.tracer is not None:
                self.tracer.close()
            # saves are async (checkpoint.py): the loop's last scheduled save
            # must land before the run dir is read (resume, serving export) —
            # and so a clean shutdown never leaves a half-written step
            self.ckpt.finish()

    def _train_loop_episodic(self, episodes, train_state, rollout_state, key):
        """K=1 loop: two dispatches (collect, train) per episode."""
        run = self.run_cfg
        self.flight.iters_per_dispatch = 1
        # episode accounting (dcml_runner.py:29-74)
        E = run.n_rollout_threads
        acc_rew = np.zeros(E)
        acc_delay = np.zeros(E)
        acc_pay = np.zeros(E)
        done_rewards, done_delays, done_payments = [], [], []
        # on-device accounting aggregates (collectors emitting chunk_stats)
        agg_done = agg_rew = agg_delay = agg_pay = 0.0

        tel = self.telemetry
        env = getattr(self, "env", None) or getattr(self.collector, "env", None)
        n_agents = int(getattr(env, "n_agents", 1) or 1)
        tel.start_interval()

        start = time.time()
        for episode in range(self.start_episode, episodes):
            self._graceful_stop_check(episode, train_state, rollout_state, key)
            # crash-path snapshot (no donation here, so no retry use — this
            # feeds the unhandled-exception emergency checkpoint).  Host-driven
            # collectors may carry non-array state pack_tree can't deep-copy.
            if getattr(self.collector, "jittable", True):
                self.watchdog.arm(episode, train_state, rollout_state, key)
            self.profile_window.tick()
            # profile ONE post-warmup iteration (episode start+1: compiles are
            # done, steady-state schedule) — the jax.profiler hook the
            # reference lacked entirely (SURVEY.md §5 tracing)
            profiling = (
                run.profile_dir is not None and episode == self.start_episode + 1
                and not self.profile_window.active
            )
            # blocking step timers + NaN-guard fetch every telemetry_interval
            # iterations (cheap — the collect->train chain is serially
            # dependent anyway, the sync only pins wall time to a phase)
            sampled = run.telemetry_interval > 0 and (
                (episode - self.start_episode) % run.telemetry_interval == 0
            )
            # flight recorder: the iteration's inputs, including the pre-split
            # key, so a bundle replays this episode from here
            self.flight.snapshot(episode, train_state, rollout_state, key)
            # sampled span trace for this episode (Tracer does its own
            # deterministic sampling); a live trace forces the phase syncs so
            # its collect/train spans measure real wall time, same cost as a
            # sampled-telemetry episode
            trace = (self.tracer.start_trace("training", root="dispatch")
                     if self.tracer is not None else None)
            if profiling:
                jax.profiler.start_trace(run.profile_dir)
            try:
                t_collect = time.perf_counter()
                rollout_state, traj = self._collect(train_state.params, rollout_state)
                if profiling or sampled or trace is not None:
                    jax.block_until_ready(traj)
                    t_end = time.perf_counter()
                    if trace is not None:
                        trace.add_span("collect", t_collect, t_end)
                    t_collect = t_end - t_collect
                    if sampled:
                        tel.observe("step_time_collect", t_collect)
                key, k_train = jax.random.split(key)
                t_train = time.perf_counter()
                train_state, metrics = self._train(
                    train_state, traj, self._bootstrap(rollout_state), k_train
                )
                if profiling or sampled or trace is not None:
                    jax.block_until_ready(train_state)
                    t_end = time.perf_counter()
                    if trace is not None:
                        trace.add_span("train", t_train, t_end)
                    t_train = t_end - t_train
                    if sampled:
                        tel.observe("step_time_train", t_train)
            finally:
                # an exception mid-iteration must still terminate the trace —
                # an unterminated capture leaves a corrupt xplane.pb
                if profiling:
                    jax.profiler.stop_trace()
            if profiling:
                self.log(
                    f"[profile] trace -> {run.profile_dir}; compiled-step wall: "
                    f"collect {t_collect:.3f}s train {t_train:.3f}s"
                )
                self.writer.write(
                    {"episode": episode, "profile_collect_sec": t_collect,
                     "profile_train_sec": t_train},
                    step=episode,
                )

            tel.count("env_steps", run.episode_length * E)
            tel.count("agent_steps", run.episode_length * E * n_agents)
            total_steps = (episode + 1) * run.episode_length * E
            if sampled:
                # one small blocking fetch covers the NaN guard AND the
                # tripwire signals
                t_fetch = time.perf_counter()
                health = jax.device_get({
                    "nonfinite_grads": getattr(metrics, "nonfinite_grads", 0.0),
                    "grad_norm": getattr(metrics, "grad_norm", 0.0),
                    "param_norm": getattr(metrics, "param_norm", 0.0),
                    "update_ratio": getattr(metrics, "update_ratio", 0.0),
                })
                if trace is not None:
                    trace.add_span("fetch", t_fetch, time.perf_counter())
                nf = float(np.sum(np.asarray(health["nonfinite_grads"])))
                tel.count("nonfinite_grad_steps", nf)
                if self.anomaly is not None:
                    signals = {
                        "nonfinite_grads": nf,
                        "grad_norm": float(np.max(np.asarray(health["grad_norm"]))),
                        "param_norm": float(np.max(np.asarray(health["param_norm"]))),
                        "update_ratio": float(np.max(np.asarray(health["update_ratio"]))),
                        "steady_state_recompiles":
                            tel.counters.get("steady_state_recompiles", 0.0),
                        "dispatch_fused_fallback": self._fused_fallback,
                        "step_time_collect": t_collect,
                        "step_time_train": t_train,
                    }
                    trips = self.anomaly.observe(self._chaos_signals(signals),
                                                 episode, total_steps)
                    if trips:
                        reference = self._metrics_reference(metrics)
                        self._handle_anomalies(trips, episode, total_steps, reference)
            if episode == self.start_episode:
                self._mark_steady()

            stats = getattr(traj, "chunk_stats", None)
            if stats is not None:
                # on-device accounting: only these scalars cross to the host —
                # the (T, E, A) reward/done tensors stay on device, which
                # matters on tunneled backends
                stats = {k: float(v) for k, v in jax.device_get(stats).items()}
                agg_done += stats["n_done"]
                agg_rew += stats["done_reward_sum"]
                # AC collectors omit the info channels on envs without them
                has_info = "done_delay_sum" in stats
                agg_delay += stats.get("done_delay_sum", 0.0)
                agg_pay += stats.get("done_payment_sum", 0.0)
                if "spec_draft_passes" in stats:
                    # speculative decode health: block passes per decode (K̄ =
                    # n_agent / draft_passes) and the draft acceptance rate
                    tel.gauge("decode_spec_draft_passes", stats["spec_draft_passes"])
                    tel.gauge("decode_spec_verify_passes", stats["spec_verify_passes"])
                    off = stats["spec_drafts_offered"]
                    acc = stats["spec_drafts_accepted"]
                    tel.gauge("decode_spec_accept_rate",
                              acc / off if off > 0 else 1.0)
            else:
                # host-side episode metric accumulation (one device->host copy)
                rew_arr = np.asarray(traj.rewards)             # (T, E, A, n_obj)
                # sum objective channels (== scalar reward), mean over agents
                rew = rew_arr.sum(axis=3).mean(axis=2)         # (T, E)
                has_info = traj.delays is not None
                delays = np.asarray(traj.delays) if has_info else np.zeros_like(rew)
                pays = np.asarray(traj.payments) if has_info else np.zeros_like(rew)
                dones = np.asarray(traj.dones)
                for t in range(rew.shape[0]):
                    acc_rew += rew[t]
                    acc_delay += delays[t]
                    acc_pay += pays[t]
                    finished = dones[t]
                    if finished.any():
                        done_rewards.extend(acc_rew[finished].tolist())
                        done_delays.extend(acc_delay[finished].tolist())
                        done_payments.extend(acc_pay[finished].tolist())
                        acc_rew[finished] = 0
                        acc_delay[finished] = 0
                        acc_pay[finished] = 0

            # the first episode after a resume always logs, so every run
            # contributes at least one metrics record
            if episode % run.log_interval == 0 or episode == self.start_episode:
                elapsed = time.time() - start
                # fps counts only steps run in THIS process (correct after a
                # --model_dir resume, where total_steps includes prior runs)
                steps_here = (episode + 1 - self.start_episode) * run.episode_length * E
                fps = steps_here / max(elapsed, 1e-9)
                record = {
                    "episode": episode,
                    "total_steps": total_steps,
                    "fps": fps,
                    "average_step_rewards": (
                        stats["step_reward_mean"] if stats is not None
                        else float(rew_arr.sum(-1).mean())
                    ),
                    # stacked per-agent trainers (ippo) report per-agent
                    # metric vectors; log the mean over agents
                    "value_loss": float(np.mean(metrics.value_loss)),
                    "policy_loss": float(np.mean(metrics.policy_loss)),
                    "dist_entropy": float(np.mean(metrics.dist_entropy)),
                    "grad_norm": float(np.mean(getattr(metrics, "grad_norm", 0.0))),
                    "param_norm": float(np.mean(getattr(metrics, "param_norm", 0.0))),
                    "update_ratio": float(np.mean(getattr(metrics, "update_ratio", 0.0))),
                    "ratio": float(np.mean(getattr(metrics, "ratio", 1.0))),
                }
                if stats is not None:
                    # per-objective channel means (dcml_runner.py:306-309)
                    for k, v in stats.items():
                        if k.startswith("step_objective_"):
                            i = k.split("_")[2]
                            record[f"average_step_objective_{i}"] = v
                    if agg_done > 0:
                        record["aver_episode_rewards"] = agg_rew / agg_done
                        if has_info:
                            record["aver_episode_delays"] = agg_delay / agg_done
                            record["aver_episode_payments"] = agg_pay / agg_done
                        agg_done = agg_rew = agg_delay = agg_pay = 0.0
                else:
                    if rew_arr.shape[-1] > 1:
                        for i in range(rew_arr.shape[-1]):
                            record[f"average_step_objective_{i}"] = float(rew_arr[..., i].mean())
                    if done_rewards:
                        record["aver_episode_rewards"] = float(np.mean(done_rewards))
                        if has_info:
                            record["aver_episode_delays"] = float(np.mean(done_delays))
                            record["aver_episode_payments"] = float(np.mean(done_payments))
                        done_rewards, done_delays, done_payments = [], [], []
                for k, v in device_memory_gauges().items():
                    tel.gauge(k, v)
                tel.gauge("host_rss_bytes", host_rss_bytes())
                record.update(tel.flush())
                self._extra_metrics(record)
                self._log_record(record)

            should_save = run.save_interval > 0 and (
                episode % run.save_interval == 0 or episode == episodes - 1
            )
            if should_save and self.run_cfg.algorithm_name != "random":
                t_ckpt = time.perf_counter()
                self.ckpt.save(episode, train_state)
                if trace is not None:
                    # saves are async — this span is the host-side schedule
                    # cost, what the training loop actually pays
                    trace.add_span("checkpoint", t_ckpt, time.perf_counter())
            if trace is not None:
                trace.finish(status="ok", episode=episode)

            if run.use_eval and episode % run.eval_interval == 0 and hasattr(self, "evaluate"):
                # each runner's evaluate has protocol-appropriate defaults
                # (steps for DCML/mujoco, episodes for SMAC)
                eval_info = self.evaluate(train_state)
                eval_info.update(episode=episode, total_steps=total_steps)
                self.writer.write(eval_info, step=total_steps)
                self.log(f"eval ep {episode}: {eval_info}")

        return train_state, rollout_state

    # ------------------------------------------------------- fused dispatch

    def _train_loop_fused(self, episodes, train_state, rollout_state, key, K: int):
        """K>1 loop: one donated jitted dispatch advances K episodes, metrics
        come back as stacked ``(K,)`` scalars fetched asynchronously — the
        host formats and logs dispatch N-1 while dispatch N runs on device.
        Log/save/eval cadences snap up to dispatch boundaries
        (:func:`_cadence_hits`); the episode count rounds up to whole
        dispatches so every dispatch compiles to the same program."""
        run = self.run_cfg
        tel = self.telemetry
        E = run.n_rollout_threads
        T = run.episode_length
        env = getattr(self, "env", None) or getattr(self.collector, "env", None)
        n_agents = int(getattr(env, "n_agents", 1) or 1)
        self.flight.iters_per_dispatch = K

        self._dispatch = instrumented_jit(
            make_dispatch_fn(self.trainer, self.collector, K,
                             state_shardings=self._state_shardings()),
            "dispatch", tel, self.log, donate_argnums=(0, 1),
            count_collectives=self.mesh is not None,
        )
        self._dispatch_iters = K
        tel.gauge("iters_per_dispatch", float(K))
        tel.rate("dispatch_count", "dispatches_per_sec")

        first = self.start_episode
        n_disp = -(-(episodes - first) // K)
        if n_disp <= 0:
            # resumed past the requested budget: nothing to run, and the
            # trailing boundary/process below assume >= 1 dispatch happened
            self.log(f"[dispatch] resume at episode {first} >= requested "
                     f"{episodes} episodes; nothing to train")
            return train_state, rollout_state
        if first + n_disp * K != episodes:
            self.log(f"[dispatch] {episodes - first} episodes round up to "
                     f"{n_disp} dispatches of {K}")
        agg = {"done": 0.0, "rew": 0.0, "delay": 0.0, "pay": 0.0, "has_info": False}
        tel.start_interval()
        start = time.time()

        def process(d, ep_last, fetch, t_launch, trace):
            # blocks only on compute still in flight for THIS dispatch — the
            # next one is already enqueued, so the device never idles on the
            # host-side formatting below
            t_get = time.perf_counter()
            try:
                metrics, stats = fetch.get()
            except Exception as e:
                # a failed fetch must not leave a half-formed record behind:
                # count it, log it, and skip this dispatch's bookkeeping
                tel.count("deferred_fetch_errors")
                self.log(f"[telemetry] deferred fetch failed for dispatch {d}: {e!r}")
                if trace is not None:
                    trace.finish(status="error", episode=ep_last)
                return
            t_done = time.perf_counter()
            if trace is not None:
                # fused collect+train is one program: "dispatch" spans
                # launch -> results-landed; "fetch" is the host-block tail
                trace.add_span("dispatch", t_launch, t_done, iters=K)
                trace.add_span("fetch", t_get, t_done)
                trace.finish(end=t_done, status="ok", episode=ep_last)
            timed = run.telemetry_interval > 0 and d % run.telemetry_interval == 0
            if timed:
                # sync-free derived timer: get() returns when this dispatch's
                # results landed, so done-minus-launch is its wall duration
                tel.observe("step_time_dispatch", t_done - t_launch)
                tel.observe("step_time_host_block", t_done - t_get)
            # count work at COMPLETION, not enqueue (launches are async and
            # would front-run the device — registry.py rate semantics)
            tel.count("env_steps", T * E * K)
            tel.count("agent_steps", T * E * K * n_agents)
            tel.count("dispatch_count")
            nf = float(np.sum(np.asarray(getattr(metrics, "nonfinite_grads", 0.0))))
            tel.count("nonfinite_grad_steps", nf)
            if self.anomaly is not None:
                # metrics are already host numpy (DeferredFetch resolved) —
                # detection runs every dispatch at zero extra transfer cost.
                # Spike signals take the max over the K stacked iterations.
                signals = {
                    "nonfinite_grads": nf,
                    "grad_norm": float(np.max(np.asarray(
                        getattr(metrics, "grad_norm", 0.0)))),
                    "param_norm": float(np.max(np.asarray(
                        getattr(metrics, "param_norm", 0.0)))),
                    "update_ratio": float(np.max(np.asarray(
                        getattr(metrics, "update_ratio", 0.0)))),
                    "steady_state_recompiles":
                        tel.counters.get("steady_state_recompiles", 0.0),
                    "dispatch_fused_fallback": self._fused_fallback,
                }
                if timed:
                    signals["step_time_dispatch"] = t_done - t_launch
                trips = self.anomaly.observe(self._chaos_signals(signals),
                                             ep_last, (ep_last + 1) * T * E)
                if trips:
                    reference = self._metrics_reference(metrics, stats)
                    # the bundle targets the FIRST episode of this dispatch —
                    # its snapshot is the dispatch's input state
                    self._handle_anomalies(trips, ep_last - K + 1,
                                           (ep_last + 1) * T * E, reference)
            stats = {k: np.asarray(v) for k, v in stats.items()}
            if "spec_draft_passes" in stats:
                # stacked (K,) per-iteration values -> dispatch-level gauges
                tel.gauge("decode_spec_draft_passes",
                          float(np.mean(stats["spec_draft_passes"])))
                tel.gauge("decode_spec_verify_passes",
                          float(np.mean(stats["spec_verify_passes"])))
                off = float(np.sum(stats["spec_drafts_offered"]))
                acc = float(np.sum(stats["spec_drafts_accepted"]))
                tel.gauge("decode_spec_accept_rate", acc / off if off > 0 else 1.0)
            agg["done"] += float(stats["n_done"].sum())
            agg["rew"] += float(stats["done_reward_sum"].sum())
            if "done_delay_sum" in stats:
                agg["has_info"] = True
                agg["delay"] += float(stats["done_delay_sum"].sum())
                agg["pay"] += float(stats["done_payment_sum"].sum())
            if not (d == 0 or _cadence_hits(run.log_interval, ep_last - K + 1, K)):
                return
            total_steps = (ep_last + 1) * T * E
            elapsed = time.time() - start
            fps = (ep_last + 1 - first) * T * E / max(elapsed, 1e-9)
            record = {
                "episode": ep_last,
                "total_steps": total_steps,
                "fps": fps,
                # stacked (K,) per-iteration metrics -> means over the dispatch
                "average_step_rewards": float(np.mean(stats["step_reward_mean"])),
                "value_loss": float(np.mean(metrics.value_loss)),
                "policy_loss": float(np.mean(metrics.policy_loss)),
                "dist_entropy": float(np.mean(metrics.dist_entropy)),
                "grad_norm": float(np.mean(getattr(metrics, "grad_norm", 0.0))),
                "param_norm": float(np.mean(getattr(metrics, "param_norm", 0.0))),
                "update_ratio": float(np.mean(getattr(metrics, "update_ratio", 0.0))),
                "ratio": float(np.mean(getattr(metrics, "ratio", 1.0))),
            }
            for k, v in stats.items():
                if k.startswith("step_objective_"):
                    i = k.split("_")[2]
                    record[f"average_step_objective_{i}"] = float(np.mean(v))
            if agg["done"] > 0:
                record["aver_episode_rewards"] = agg["rew"] / agg["done"]
                if agg["has_info"]:
                    record["aver_episode_delays"] = agg["delay"] / agg["done"]
                    record["aver_episode_payments"] = agg["pay"] / agg["done"]
                agg.update(done=0.0, rew=0.0, delay=0.0, pay=0.0)
            for k, v in device_memory_gauges().items():
                tel.gauge(k, v)
            tel.gauge("host_rss_bytes", host_rss_bytes())
            record.update(tel.flush())
            self._extra_metrics(record)
            self._log_record(record)

        def boundary(ep0, ep_last, state, final):
            should_save = run.save_interval > 0 and (
                _cadence_hits(run.save_interval, ep0, K) or final
            )
            if should_save and run.algorithm_name != "random":
                self.ckpt.save(ep_last, state)
            if run.use_eval and _cadence_hits(run.eval_interval, ep0, K) and hasattr(self, "evaluate"):
                eval_info = self.evaluate(state)
                eval_info.update(episode=ep_last, total_steps=(ep_last + 1) * T * E)
                self.writer.write(eval_info, step=(ep_last + 1) * T * E)
                self.log(f"eval ep {ep_last}: {eval_info}")

        pending = None       # (d, ep_last, fetch, t_launch, trace) in flight
        for d in range(n_disp):
            ep0 = first + d * K
            # graceful stop lands HERE: the carry is whole (outputs of
            # dispatch d-1, not yet donated) — the only point a full-state
            # emergency checkpoint is possible
            self._graceful_stop_check(ep0, train_state, rollout_state, key)
            self.profile_window.tick()
            # checkpoint/eval for the previous dispatch boundary must run
            # BEFORE this dispatch donates (invalidates) train_state's buffers
            if d > 0:
                t_ckpt = time.perf_counter()
                boundary(ep0 - K, ep0 - 1, train_state, final=False)
                if pending is not None and pending[4] is not None:
                    # the boundary belongs to the PREVIOUS dispatch's episodes
                    # — attach its span there (process() finishes that trace
                    # a few lines below, after this dispatch launches)
                    pending[4].add_span("checkpoint", t_ckpt,
                                        time.perf_counter())
            # snapshot-before-donate: the dispatch about to launch invalidates
            # these buffers, and its metrics are only inspected one dispatch
            # later — the ring (depth >= 2) is what still holds this state
            # when a tripwire fires
            self.flight.snapshot(ep0, train_state, rollout_state, key)
            # watchdog snapshot (same pre-donation constraint): feeds dispatch
            # retries and the crash-path emergency checkpoint
            self.watchdog.arm(ep0, train_state, rollout_state, key)
            profiling = (run.profile_dir is not None and d == 1
                         and not self.profile_window.active)
            trace = (self.tracer.start_trace("training", root="dispatch")
                     if self.tracer is not None else None)
            if profiling:
                jax.profiler.start_trace(run.profile_dir)
            try:
                if _chaos.ACTIVE is not None:
                    _chaos.ACTIVE.on_dispatch()
                t_launch = time.perf_counter()
                train_state, rollout_state, key, stacked = self.watchdog.run(
                    self._dispatch, train_state, rollout_state, key
                )
                if profiling:
                    jax.block_until_ready(train_state)
                    dt = time.perf_counter() - t_launch
            finally:
                # exception between start/stop must not leave the trace open
                if profiling:
                    jax.profiler.stop_trace()
            if profiling:
                self.log(f"[profile] trace -> {run.profile_dir}; compiled-"
                         f"dispatch wall: {dt:.3f}s for {K} iterations")
                self.writer.write(
                    {"episode": ep0 + K - 1, "profile_dispatch_sec": dt},
                    step=ep0 + K - 1,
                )
            fetch = DeferredFetch(stacked)
            if d == 0:
                self._mark_steady()
                tel.start_interval()   # rates measure steady state, not the
                                       # one large fused warmup compile
            if pending is not None:
                process(*pending)      # overlaps dispatch d running on device
            pending = (d, ep0 + K - 1, fetch, t_launch, trace)

        t_ckpt = time.perf_counter()
        boundary(first + (n_disp - 1) * K, first + n_disp * K - 1, train_state,
                 final=True)
        if pending[4] is not None:
            pending[4].add_span("checkpoint", t_ckpt, time.perf_counter())
        process(*pending)
        return train_state, rollout_state

    # ---------------------------------------------------- async actor-learner

    def _train_loop_async(self, episodes, train_state, rollout_state, key):
        """--async_actors: overlap collect and train on disjoint submeshes
        (training/async_loop.py; Podracer sebulba).  N actor THREADS
        (``--async_actor_workers``) each run a jitted collector continuously
        on their carved slice of the actor submesh and feed one shared
        :class:`TrajectoryStore`; this method IS the learner program and
        stays on the main thread (signal handlers, checkpoint writes).  One
        consumed block = one episode, so episode accounting, cadences, and
        resume counters match the synchronous loops.

        Staleness: the store's admission control bounds the param-version lag
        of every consumed block at ``--staleness_budget`` (1 = PR 13's
        double-buffered overlap); with a budget > 1 the V-trace-style
        truncated-IS correction (``--off_policy_correction``,
        training/off_policy.py) reweights each stale block's PPO update.

        Not bit-exact with the synchronous loop (lagged PPO, separate
        actor/learner PRNG consumption); the graceful-stop carry is coherent —
        learner state at a step boundary + worker 0's last completed rollout
        state — but a resumed run replays any unconsumed actor work (workers
        1..N-1 re-derive their decorrelated carries from worker 0's on
        resume).
        """
        run = self.run_cfg
        tel = self.telemetry
        E = run.n_rollout_threads
        T = run.episode_length
        env = getattr(self, "env", None) or getattr(self.collector, "env", None)
        n_agents = int(getattr(env, "n_agents", 1) or 1)
        self.flight.iters_per_dispatch = 1

        from mat_dcml_tpu.parallel.distributed import (
            put_replicated,
            put_sharded_state,
        )
        from mat_dcml_tpu.parallel.mesh import (
            build_actor_learner_meshes,
            carve_actor_worker_meshes,
        )
        from mat_dcml_tpu.telemetry.aggregate import TelemetryAggregator
        from mat_dcml_tpu.training.async_loop import (
            ActorDeadError,
            ActorWorker,
            ParamPublisher,
            TrajectoryStore,
        )
        from mat_dcml_tpu.training import off_policy as off_policy_mod
        from jax.sharding import NamedSharding, PartitionSpec as P

        n_workers = int(getattr(run, "async_actor_workers", 1))
        budget = int(getattr(run, "staleness_budget", 1))
        actor_mesh, learner_mesh = build_actor_learner_meshes(
            int(getattr(run, "actor_devices", 0)),
            int(getattr(run, "learner_devices", 0)),
        )
        worker_meshes = carve_actor_worker_meshes(actor_mesh, n_workers)
        for side, m in (("actor", worker_meshes[0]), ("learner", learner_mesh)):
            n_data = dict(m.shape)["data"]
            if E % n_data:
                raise ValueError(
                    f"--n_rollout_threads {E} must be divisible by the "
                    f"{side} submesh's data axis ({n_data} devices); adjust "
                    f"--actor_devices/--learner_devices"
                )
        # the learner owns train_state + PRNG chain; actors own the env state
        train_state = put_replicated(train_state, learner_mesh)
        key = jax.device_put(key, NamedSharding(learner_mesh, P()))
        # worker 0 keeps the provided carry (PR 13 parity + what a graceful
        # stop packs); workers 1..N-1 decorrelate by folding their index into
        # the rollout PRNG, so N slices explore N distinct trajectories
        rollout_states = []
        for i, wm in enumerate(worker_meshes):
            rs_i = rollout_state
            if i > 0 and getattr(rs_i, "rng", None) is not None:
                rs_i = rs_i._replace(rng=jax.random.fold_in(rs_i.rng, i))
            rollout_states.append(put_sharded_state(rs_i, wm))

        # every actor program gets a PRIVATE telemetry registry (jit
        # instrumentation is not thread-safe across threads); the aggregator
        # is the merged read-side view (async_actor_* in records), with each
        # registry also flushed under its own async_actor_w<i>_ label
        actor_agg = TelemetryAggregator()
        actor_tels, collect_jits = [], []
        for i in range(n_workers):
            t_i = Telemetry()
            actor_agg.add_source(f"w{i}", t_i)
            actor_tels.append(t_i)
            collect_jits.append(instrumented_jit(
                self.collector.collect, "collect", t_i, self.log
            ))
        # donation is safe against the publisher: publish() blocks until the
        # params copy lands on the actor submesh, so the next donating update
        # can never invalidate buffers a device-to-device copy still reads
        train_jit = instrumented_jit(
            self.trainer.train, "train", tel, self.log, donate_argnums=(0,),
            count_collectives=dict(learner_mesh.shape)["data"] > 1,
        )
        publisher = ParamPublisher(worker_meshes)
        publisher.publish(train_state.params)
        # ring capacity never throttles the staleness budget: admission is
        # the real gate, the ring just holds what admission granted
        store = TrajectoryStore(
            max(1, int(getattr(run, "async_queue_depth", 2)), budget),
            staleness_budget=budget,
        )

        def make_worker(i, rs):
            return ActorWorker(collect_jits[i], publisher, store, rs,
                               learner_mesh, telemetry=actor_tels[i],
                               log=self.log, worker_id=i)

        workers = [make_worker(i, rollout_states[i])
                   for i in range(n_workers)]
        # importance correction (async_loop.ImportanceCorrection): an
        # explicitly set self.importance_correction wins; otherwise
        # --off_policy_correction decides ("auto" = V-trace iff budget > 1).
        # The params_fn closure reads this scope's train_state binding at
        # call time, so the hook always scores under the newest params.
        correction = getattr(self, "importance_correction", None)
        vtrace_on = off_policy_mod.resolve_correction_mode(
            str(getattr(run, "off_policy_correction", "auto")), budget)
        if correction is None and vtrace_on:
            tr_cfg = getattr(self.trainer, "cfg", None)
            factory = (off_policy_mod.make_vtrace_correction if self.is_mat
                       else off_policy_mod.make_ac_vtrace_correction)
            correction = factory(
                self.policy, lambda: train_state.params,
                rho_bar=float(getattr(tr_cfg, "vtrace_rho_bar", 1.0)),
                c_bar=float(getattr(tr_cfg, "vtrace_c_bar", 1.0)),
                telemetry=tel,
            )
        tel.gauge("async_actor_devices", float(actor_mesh.size))
        tel.gauge("async_learner_devices", float(learner_mesh.size))
        tel.gauge("async_actor_workers", float(n_workers))
        tel.gauge("store_staleness_budget", float(budget))
        self.log(f"[async] actor submesh {actor_mesh.size}d carved into "
                 f"{n_workers} worker(s) / learner submesh "
                 f"{learner_mesh.size}d, store capacity {store.capacity}, "
                 f"staleness budget {budget}, correction "
                 f"{'vtrace' if (vtrace_on or correction is not None) else 'none'}")

        def quiesce():
            """Graceful-stop half of the async contract: stop every worker at
            an iteration boundary, discard in-flight blocks (a resumed run
            replays them), hand back worker 0's last COMPLETED rollout
            state."""
            for w in workers:
                w.request_stop()
            store.close()
            for w in workers:
                w.join(timeout=60.0)
            discarded = len(store.drain())
            iters = ", ".join(f"w{w.worker_id}:{w.iterations}"
                              for w in workers)
            self.log(f"[async] stop: {len(workers)} worker(s) joined "
                     f"({iters}); {discarded} queued block(s) discarded")
            return workers[0].latest_rollout_state

        first = self.start_episode
        agg_done = agg_rew = agg_delay = agg_pay = 0.0
        has_info = False
        restarts = [0] * n_workers
        max_restarts = max(0, int(getattr(run, "async_actor_max_restarts", 2)))
        tel.start_interval()
        start = time.time()
        for w in workers:
            w.start()
        try:
            for episode in range(first, episodes):
                self._graceful_stop_check(episode, train_state,
                                          workers[0].latest_rollout_state,
                                          key, before_pack=quiesce)
                # crash-path snapshot: learner-boundary train_state/key + the
                # actor's newest completed carry (rebind-safe: the actor swaps
                # the reference, never mutates a published tree)
                self.watchdog.arm(episode, train_state,
                                  workers[0].latest_rollout_state, key)
                self.profile_window.tick()
                sampled = run.telemetry_interval > 0 and (
                    (episode - first) % run.telemetry_interval == 0
                )
                trace = (self.tracer.start_trace("training", root="learner_step")
                         if self.tracer is not None else None)
                t_wait = time.perf_counter()

                def check_workers():
                    # per-worker liveness: a thread that died WITHOUT
                    # recording an error (crashed C extension, injected
                    # actor_crash chaos) would otherwise go unnoticed — with
                    # a live sibling still feeding the store the learner
                    # never starves, so this runs every consume, not just
                    # when the store runs dry.  Restart from the last
                    # published params + the dead worker's last completed
                    # rollout state, up to the per-worker budget; reclaim
                    # any admission ticket it died holding so the staleness
                    # budget never leaks.
                    for w in workers:
                        if w.error is not None:
                            raise DispatchFailedError(
                                f"actor program failed: {w.error!r}"
                            ) from w.error
                    for i, w in enumerate(workers):
                        if w.is_alive() or w.stop_requested:
                            continue
                        restarts[i] += 1
                        if restarts[i] > max_restarts:
                            raise ActorDeadError(
                                f"actor worker w{i} died silently "
                                f"{restarts[i]} time(s) — restart budget "
                                f"({max_restarts}) spent; last completed "
                                f"iteration {w.iterations}")
                        self.log(f"[async] actor worker w{i} dead with no "
                                 f"recorded error after iteration "
                                 f"{w.iterations}; restarting from last "
                                 f"published params "
                                 f"({restarts[i]}/{max_restarts})")
                        tel.count("async_actor_restarts")
                        if getattr(w, "holding_ticket", False):
                            store.cancel_ticket()
                        workers[i] = make_worker(i, w.latest_rollout_state)
                        workers[i].start()

                check_workers()
                block = store.get(timeout=0.25)
                while block is None:
                    check_workers()
                    self._graceful_stop_check(episode, train_state,
                                              workers[0].latest_rollout_state,
                                              key, before_pack=quiesce)
                    block = store.get(timeout=0.25)
                t_got = time.perf_counter()
                # staleness: learner steps published since this block's params
                lag = publisher.version - block.param_version
                tel.hist("staleness_learner_steps", float(lag))
                tel.gauge("staleness_param_version", float(publisher.version))
                tel.hist("async_queue_wait_ms", (t_got - t_wait) * 1e3)
                tel.gauge("async_queue_depth", float(store.depth))
                tel.gauge("store_depth", float(store.depth))
                tel.gauge("store_tickets", float(store.tickets))
                traj = block.traj
                if correction is not None:
                    # applied at lag 0 too (numerical identity) so the jitted
                    # update's pytree structure never flips mid-run — see
                    # off_policy.py docstring
                    traj = correction(traj, lag)
                key, k_train = jax.random.split(key)
                t_train = time.perf_counter()
                train_state, metrics = train_jit(
                    train_state, traj, self._bootstrap(block.rollout_state),
                    k_train,
                )
                # the learner's next act (publish) needs the params anyway;
                # blocking here costs nothing — the actor submesh keeps
                # collecting while this thread waits
                jax.block_until_ready(train_state)
                t_end = time.perf_counter()
                publisher.publish(train_state.params)
                # the consumed block stops counting against the staleness
                # budget only now — AFTER its update was published — so a
                # block admitted during the train window still lands within B
                store.mark_consumed()
                if trace is not None:
                    trace.add_span("actor_iter", block.t_start, block.t_end,
                                   actor_iter=block.actor_iter,
                                   param_version=block.param_version)
                    trace.add_span("queue_wait", t_wait, t_got)
                    trace.add_span("train", t_train, t_end)
                if sampled:
                    tel.observe("step_time_collect", block.t_end - block.t_start)
                    tel.observe("step_time_train", t_end - t_train)
                tel.count("env_steps", T * E)
                tel.count("agent_steps", T * E * n_agents)
                tel.count("async_learner_steps")
                total_steps = (episode + 1) * T * E
                if episode == first:
                    # learner warmup done (the actor marks its own collect jit
                    # steady after its first iteration)
                    if isinstance(train_jit, InstrumentedJit):
                        train_jit.mark_steady()
                        if train_jit.bytes_per_call is not None:
                            tel.gauge("bytes_per_update",
                                      float(train_jit.bytes_per_call))
                    n_compiles = int(tel.counters.get("compile_count", 0))
                    secs = tel.counters.get("compile_seconds_total", 0.0)
                    self.log(f"[telemetry] learner warmup done: {n_compiles} "
                             f"compiles in {secs:.1f}s")
                    tel.start_interval()
                if sampled:
                    health = jax.device_get({
                        "nonfinite_grads": getattr(metrics, "nonfinite_grads", 0.0),
                        "grad_norm": getattr(metrics, "grad_norm", 0.0),
                        "param_norm": getattr(metrics, "param_norm", 0.0),
                        "update_ratio": getattr(metrics, "update_ratio", 0.0),
                    })
                    nf = float(np.sum(np.asarray(health["nonfinite_grads"])))
                    tel.count("nonfinite_grad_steps", nf)
                    if self.anomaly is not None:
                        signals = {
                            "nonfinite_grads": nf,
                            "grad_norm": float(np.max(np.asarray(health["grad_norm"]))),
                            "param_norm": float(np.max(np.asarray(health["param_norm"]))),
                            "update_ratio": float(np.max(np.asarray(health["update_ratio"]))),
                            "steady_state_recompiles":
                                tel.counters.get("steady_state_recompiles", 0.0),
                            "step_time_collect": block.t_end - block.t_start,
                            "step_time_train": t_end - t_train,
                        }
                        trips = self.anomaly.observe(
                            self._chaos_signals(signals), episode, total_steps)
                        if trips:
                            reference = self._metrics_reference(metrics)
                            self._handle_anomalies(trips, episode, total_steps,
                                                   reference)

                stats = getattr(traj, "chunk_stats", None)
                if stats is not None:
                    stats = {k: float(v) for k, v in jax.device_get(stats).items()}
                    agg_done += stats["n_done"]
                    agg_rew += stats["done_reward_sum"]
                    has_info = "done_delay_sum" in stats
                    agg_delay += stats.get("done_delay_sum", 0.0)
                    agg_pay += stats.get("done_payment_sum", 0.0)

                if episode % run.log_interval == 0 or episode == first:
                    elapsed = time.time() - start
                    steps_here = (episode + 1 - first) * T * E
                    fps = steps_here / max(elapsed, 1e-9)
                    record = {
                        "episode": episode,
                        "total_steps": total_steps,
                        "fps": fps,
                        "average_step_rewards": (
                            stats["step_reward_mean"] if stats is not None
                            else float(np.asarray(traj.rewards).sum(-1).mean())
                        ),
                        "value_loss": float(np.mean(metrics.value_loss)),
                        "policy_loss": float(np.mean(metrics.policy_loss)),
                        "dist_entropy": float(np.mean(metrics.dist_entropy)),
                        "grad_norm": float(np.mean(getattr(metrics, "grad_norm", 0.0))),
                        "param_norm": float(np.mean(getattr(metrics, "param_norm", 0.0))),
                        "update_ratio": float(np.mean(getattr(metrics, "update_ratio", 0.0))),
                        "ratio": float(np.mean(getattr(metrics, "ratio", 1.0))),
                    }
                    if stats is not None:
                        for k, v in stats.items():
                            if k.startswith("step_objective_"):
                                i = k.split("_")[2]
                                record[f"average_step_objective_{i}"] = v
                        if agg_done > 0:
                            record["aver_episode_rewards"] = agg_rew / agg_done
                            if has_info:
                                record["aver_episode_delays"] = agg_delay / agg_done
                                record["aver_episode_payments"] = agg_pay / agg_done
                            agg_done = agg_rew = agg_delay = agg_pay = 0.0
                    for k, v in device_memory_gauges().items():
                        tel.gauge(k, v)
                    tel.gauge("host_rss_bytes", host_rss_bytes())
                    tel.gauge("async_queue_drops", float(store.drops))
                    tel.gauge("async_queue_max_depth", float(store.max_depth))
                    tel.gauge("async_actor_iters",
                              float(sum(w.iterations for w in workers)))
                    tel.gauge("async_actor_workers", float(n_workers))
                    tel.gauge("store_depth", float(store.depth))
                    tel.gauge("store_max_depth", float(store.max_depth))
                    tel.gauge("store_tickets", float(store.tickets))
                    tel.gauge("store_puts", float(store.puts))
                    tel.gauge("store_gets", float(store.gets))
                    tel.gauge("store_drops", float(store.drops))
                    tel.gauge("store_workers", float(n_workers))
                    tel.gauge("store_staleness_budget", float(budget))
                    # per-worker throughput, learner-side (also what the obs
                    # sidecar's /metrics serves per actor)
                    for w in workers:
                        wid = w.worker_id
                        tel.gauge(f"async_actor_w{wid}_iters",
                                  float(w.iterations))
                        tel.gauge(
                            f"async_actor_w{wid}_env_steps_per_sec",
                            w.iterations * T * E / max(elapsed, 1e-9))
                    record.update(tel.flush())
                    # merged actor view (counters/gauges summed, histograms
                    # merged exactly across the N labelled registries) keeps
                    # the PR 13 async_actor_* keys; each worker's registry is
                    # ALSO flushed under its own async_actor_w<i>_ label so N
                    # workers never silently overwrite each other
                    record.update({f"async_actor_{k}": v
                                   for k, v in actor_agg.snapshot().items()})
                    for w in workers:
                        with w.tel_lock:
                            actor_rec = w.telemetry.flush()
                        record.update(
                            {f"async_actor_w{w.worker_id}_{k}": v
                             for k, v in actor_rec.items()})
                    self._extra_metrics(record)
                    self._log_record(record)

                should_save = run.save_interval > 0 and (
                    episode % run.save_interval == 0 or episode == episodes - 1
                )
                if should_save and run.algorithm_name != "random":
                    t_ckpt = time.perf_counter()
                    self.ckpt.save(episode, train_state)
                    if trace is not None:
                        trace.add_span("checkpoint", t_ckpt, time.perf_counter())
                if trace is not None:
                    trace.finish(status="ok", episode=episode, staleness=lag)

                if run.use_eval and episode % run.eval_interval == 0 and hasattr(self, "evaluate"):
                    eval_info = self.evaluate(train_state)
                    eval_info.update(episode=episode, total_steps=total_steps)
                    self.writer.write(eval_info, step=total_steps)
                    self.log(f"eval ep {episode}: {eval_info}")
        finally:
            # every exit path — normal, preempted, crash — must stop every
            # actor thread and release store waiters before the interpreter
            # tears down jit machinery under the daemon threads
            for w in workers:
                w.request_stop()
            store.close()
            for w in workers:
                w.join(timeout=60.0)
            leftover = len(store.drain())
            if leftover:
                self.log(f"[async] run end: {leftover} unconsumed block(s) "
                         f"discarded")
        return train_state, workers[0].latest_rollout_state

    # ------------------------------------------------------------ resilience

    def _graceful_stop_check(self, episode: int, train_state, rollout_state,
                             key, before_pack=None) -> None:
        """Honor a pending SIGTERM/SIGINT at a dispatch boundary: blocking
        emergency checkpoint of the full carry, then :class:`PreemptedExit`
        (process exit 75 — the supervisor relaunches with ``--resume auto``
        and the run continues bit-exact).

        ``before_pack``: async-overlap hook — runs only once a stop is
        actually pending, must quiesce concurrent producers (stop the actor
        thread, drain/discard in-flight queue blocks) and may return a
        replacement rollout state (the actor's last completed carry), so the
        packed snapshot is coherent at a learner-step boundary."""
        if self.stop is None or not self.stop.stop_requested:
            return
        run = self.run_cfg
        reason = self.stop.reason or "signal"
        if before_pack is not None:
            replaced = before_pack()
            if replaced is not None:
                rollout_state = replaced
        if jax.process_count() > 1 or not getattr(self.collector, "jittable",
                                                  True):
            # the packed carry needs fully-addressable arrays (and an
            # array-only rollout state); multi-host and host-driven runs fall
            # back to their latest regular checkpoint on relaunch
            self.log("[resilience] emergency carry unavailable here; resume "
                     "uses the latest regular checkpoint")
        else:
            snap = pack_carry(episode, train_state, rollout_state, key)
            self.emergency.save(snap, reason)
            self._emergency_saved_episode = episode
        latency = self.stop.latency_s()
        self.telemetry.gauge("resilience_stop_latency_s", latency)
        total_steps = episode * run.episode_length * run.n_rollout_threads
        self.writer.write(
            {"emergency_checkpoint": reason, "episode": episode,
             "total_steps": total_steps, "stop_latency_s": latency},
            step=total_steps,
        )
        self.ckpt.finish()     # in-flight async save must land too
        self.log(f"[resilience] graceful stop at episode {episode} "
                 f"({latency:.2f}s after {reason}); exiting preempted")
        raise PreemptedExit()

    def _emergency_on_failure(self, reason: str) -> None:
        """Crash path (unhandled exception, watchdog exhaustion): persist the
        watchdog's last pre-launch snapshot so the relaunch loses at most
        ``emergency_snapshot_interval`` dispatches.  Never masks the original
        error."""
        snap = self.watchdog.last_snapshot
        if snap is None or snap["episode"] == self._emergency_saved_episode:
            return
        if jax.process_count() > 1:
            return     # per-process carries are partial; rely on regular steps
        try:
            self.emergency.save(snap, f"failure: {reason}"[:200])
            self._emergency_saved_episode = snap["episode"]
            run = self.run_cfg
            total_steps = (snap["episode"] * run.episode_length
                           * run.n_rollout_threads)
            self.writer.write(
                {"emergency_checkpoint": f"failure: {reason}"[:200],
                 "episode": snap["episode"], "total_steps": total_steps},
                step=total_steps,
            )
        except Exception as e:
            self.log(f"[resilience] emergency checkpoint on failure ALSO "
                     f"failed: {e!r}")

    # ------------------------------------------------------------- anomalies

    def _metrics_reference(self, metrics, stats=None):
        """Host copy of the offending unit's train metrics (and fused
        chunk_stats), stored in the repro bundle so ``replay_bundle.py`` can
        assert bit-exact reproduction."""
        ref = {}
        if hasattr(metrics, "_fields"):
            fetched = jax.device_get(tuple(metrics))
            ref["metrics"] = {f: np.asarray(v)
                              for f, v in zip(metrics._fields, fetched)}
        if stats is not None:
            ref["stats"] = {k: np.asarray(v)
                            for k, v in jax.device_get(stats).items()}
        return ref or None

    def _chaos_signals(self, signals):
        """Chaos seam: an armed injector may mutate the anomaly-signal dict
        (nan_grad injects the *signal*, never the training math) before the
        detector observes it."""
        if _chaos.ACTIVE is not None:
            return _chaos.ACTIVE.on_anomaly_signals(signals)
        return signals

    def _handle_anomalies(self, anomalies, target_episode: int,
                          total_steps: int, reference=None) -> None:
        """A tripwire fired: emit the typed records, dump a repro bundle for
        the offending dispatch, and open the bounded profiler window.

        Under an armed chaos injector, trips the active fault plan *expects*
        are suppressed — counted and correlated to their chaos event id via a
        ``suppressed`` record, but no bundle dump and no profiler trigger, so
        injected faults don't page."""
        if _chaos.ACTIVE is not None:
            kept = []
            for a in anomalies:
                event_id = _chaos.ACTIVE.suppression_for(a.kind)
                if event_id is not None:
                    self.log(f"[anomaly] {a.kind} suppressed — expected "
                             f"under chaos event {event_id}")
                    continue
                kept.append(a)
            anomalies = kept
            if not anomalies:
                return
        for a in anomalies:
            self.log(f"[anomaly] {a.kind}: {a.signal}={a.value!r} "
                     f"baseline={a.baseline} at episode {a.episode}")
            self.writer.write(a.to_record(), step=total_steps)
            self.flight.dump(a, target_episode, reference=reference)
        self.profile_window.trigger(f"ep{target_episode}_{anomalies[0].kind}")

    def _mark_steady(self) -> None:
        """First episode (or fused dispatch) done: all warmup compiles
        happened.  Arm the recompile detector and emit ``flops_per_step``
        (compiler-counted FLOPs per env step) plus the per-entry-point
        ``bytes_per_*`` gauges (XLA cost_analysis "bytes accessed" of one
        jitted call — the statistic tests/test_update_bytes.py budgets) into
        the next metrics record."""
        if self._dispatch is not None:
            fns = {"dispatch": self._dispatch}
        else:
            fns = {"collect": self._collect, "update": self._train}
        jits = {n: j for n, j in fns.items() if isinstance(j, InstrumentedJit)}
        for j in jits.values():
            j.mark_steady()
        tel = self.telemetry
        n_compiles = int(tel.counters.get("compile_count", 0))
        secs = tel.counters.get("compile_seconds_total", 0.0)
        line = f"[telemetry] warmup done: {n_compiles} compiles in {secs:.1f}s"
        flops = [j.flops_per_call for j in jits.values()]
        if flops and all(f is not None for f in flops):
            steps = (self.run_cfg.episode_length * self.run_cfg.n_rollout_threads
                     * self._dispatch_iters)
            per_step = sum(flops) / steps
            tel.once("flops_per_step", per_step)
            line += f"; flops/env-step {per_step:.3e}"
        for name, j in jits.items():
            if j.bytes_per_call is not None:
                tel.gauge(f"bytes_per_{name}", float(j.bytes_per_call))
        if self.mesh is not None:
            # sharded-run gauges (schema family "shard_"): XLA cost_analysis
            # of a partitioned SPMD executable reports PER-DEVICE numbers, so
            # bytes_per_call IS the per-shard traffic — no division
            shape = dict(self.mesh.shape)
            tel.gauge("shard_count", float(self.mesh.size))
            tel.gauge("shard_data", float(shape.get("data", 1)))
            tel.gauge("shard_seq", float(shape.get("seq", 1)))
            tel.gauge("shard_fsdp", float(shape.get("fsdp", 1)))
            tel.gauge("shard_tp", float(shape.get("tp", 1)))
            for name, j in jits.items():
                if j.bytes_per_call is not None:
                    tel.gauge(f"shard_bytes_per_{name}", float(j.bytes_per_call))
            n_coll = [j.collectives_per_call for j in jits.values()]
            if any(c is not None for c in n_coll):
                tel.gauge("shard_psum_count",
                          float(sum(c for c in n_coll if c is not None)))
            # per-kind collective census of the steady executables — the
            # number the BENCH_FSDP expectation table checks against
            kinds: dict = {}
            for j in jits.values():
                for kind, n in (j.collective_kinds_per_call or {}).items():
                    kinds[kind] = kinds.get(kind, 0) + n
            for kind, n in kinds.items():
                tel.gauge(f"shard_param_collectives_{kind}", float(n))
            hbm = replica_hbm_high_water_bytes()
            if hbm is not None:
                tel.gauge("shard_hbm_high_water_bytes", float(hbm))
        self.log(line)

    def _extra_metrics(self, record: dict) -> None:
        """Hook for env-specific metric shaping (e.g. SMAC win rate from the
        generic episode-info channels) before a record is logged."""

    def _log_record(self, record: dict):
        self.writer.write(record, step=record.get("total_steps"))
        self._rollup_flush(record)
        self.log(
            f"ep {record['episode']} steps {record['total_steps']} fps {record['fps']:.0f} "
            f"avg_r {record['average_step_rewards']:.3f} vloss {record['value_loss']:.3f} "
            f"ploss {record['policy_loss']:.3f} ent {record['dist_entropy']:.3f}"
        )
