"""SMAC runners: battle win-rate tracking and multi-map training.

``SMACRunner`` (``runner/shared/smac_runner.py``): the generic collect/train
loop plus win-rate / dead-ratio metrics — SMAC envs emit the battle-won flag
and terminal dead ratio on the generic episode-info channels (see
``SMACTimeStep``), so per-episode sums ARE the metrics
(``smac_runner.py:70-93`` incl. ``dead_ratio`` from active masks), and an
eval-until-N-episodes deterministic loop (``:164-220``).

``SMACMultiRunner`` (``smac_multi_runner.py``): ONE policy over the universal
translated layout trained across several maps — collect on each map
round-robin, train on each map's chunk, log per-map win rates, eval over the
full map list (plus held-out maps for few-shot studies).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.scenario import (
    ScenarioEnv,
    SMACScenarioFamily,
    build_smac_scenario_set,
)
from mat_dcml_tpu.envs.smac import SMACLiteConfig, TranslatedSMACEnv
from mat_dcml_tpu.envs.smac.maps import get_map_params
from mat_dcml_tpu.training.base_runner import BaseRunner
from mat_dcml_tpu.training.generic_runner import GenericRunner, build_discrete_policy
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig
from mat_dcml_tpu.training.rollout import RolloutCollector


class SMACRunner(GenericRunner):
    """GenericRunner + SMAC metric shaping + episode-based eval."""

    def _extra_metrics(self, record: dict) -> None:
        if "aver_episode_delays" in record:
            record["win_rate"] = record.pop("aver_episode_delays")
            record["dead_ratio"] = record.pop("aver_episode_payments")

    def evaluate(self, train_state, n_episodes: int = 32, seed: int = 0,
                 max_steps: Optional[int] = None):
        """Deterministic eval until ``n_episodes`` battles finish
        (``smac_runner.py:164-220``)."""
        E = self.run_cfg.n_rollout_threads
        env = self.collector.env
        rs = self.collector.init_state(jax.random.key(seed + 17), E)
        limit = max_steps or 4 * getattr(env, "episode_limit", 200) * (
            max(n_episodes // E, 1) + 1
        )

        @jax.jit
        def eval_step(params, st):
            if self.is_mat:
                out = self.policy.get_actions(
                    params, jax.random.key(0), st.share_obs, st.obs,
                    st.available_actions, deterministic=True,
                )
                extra = {}
            else:
                out = self.collector.apply(params, jax.random.key(0), st, deterministic=True)
                extra = dict(actor_h=out.actor_h, critic_h=out.critic_h)
            env_states, ts = jax.vmap(env.step)(st.env_states, out.action)
            new_st = st._replace(
                env_states=env_states, obs=ts.obs, share_obs=ts.share_obs,
                available_actions=ts.available_actions, **extra,
            )
            done_env = ts.done.all(axis=1)
            return new_st, (done_env, ts.delay, ts.payment, ts.reward.mean())

        episodes = wins = 0
        dead_ratios, rewards = [], []
        for _ in range(limit):
            rs, (done, won, dead, rew) = eval_step(train_state.params, rs)
            done = np.asarray(done)
            rewards.append(float(rew))
            if done.any():
                episodes += int(done.sum())
                wins += int(np.asarray(won)[done].sum())
                dead_ratios.extend(np.asarray(dead)[done].tolist())
            if episodes >= n_episodes:
                break
        return {
            "eval_win_rate": wins / max(episodes, 1),
            "eval_episodes": episodes,
            "eval_dead_ratio": float(np.mean(dead_ratios)) if dead_ratios else 0.0,
            "eval_average_step_rewards": float(np.mean(rewards)),
        }


class SMACMultiRunner(BaseRunner):
    """One policy, many maps, via the universal translated layout."""

    def __init__(self, run: RunConfig, ppo: PPOConfig,
                 train_maps: Sequence[str], random_order: bool = False,
                 log_fn=print):
        if run.algorithm_name not in ("mat", "mat_dec"):
            raise NotImplementedError(
                "multi-map training drives the MAT family (smac_multi_runner.py)"
            )
        self.train_maps = tuple(train_maps)
        self.random_order = random_order
        self.envs = {m: self._make_env(m) for m in self.train_maps}
        probe = next(iter(self.envs.values()))
        self.env = probe
        self.is_mat = True
        self.policy = build_discrete_policy(run, probe)
        self.trainer = MATTrainer(self.policy, ppo, total_updates=run.episodes)
        # one collector (and jitted collect) per map — same policy params flow
        # through every one; XLA compiles one program per map shape
        self.collectors = {
            m: RolloutCollector(env, self.policy, run.episode_length)
            for m, env in self.envs.items()
        }
        self.collector = self.collectors[self.train_maps[0]]
        self.finalize(run, log_fn)
        self._collects = {m: jax.jit(c.collect) for m, c in self.collectors.items()}

    def _make_env(self, map_name: str):
        env = TranslatedSMACEnv(SMACLiteConfig(map_name=map_name))
        if self.random_order:
            # translated multi-map + per-episode shuffling reproduces the
            # Random_StarCraft2_Env_Multi combination by composition; eval
            # maps (incl. held-out) go through the same wrapper so win rates
            # are comparable across maps
            from mat_dcml_tpu.envs.permute import AgentPermutationWrapper

            env = AgentPermutationWrapper(env)
        return env

    def setup(self, seed: Optional[int] = None):
        seed = self.run_cfg.seed if seed is None else seed
        key = jax.random.key(seed)
        k_model, *k_rolls = jax.random.split(key, 1 + len(self.train_maps))
        params = self.policy.init_params(k_model)
        train_state = self.trainer.init_state(params)
        if self.run_cfg.model_dir:
            # few-shot transfer: reload the multi-task policy WEIGHTS and
            # fine-tune with a fresh optimizer/schedule — full-state restore
            # would resume the old run's (possibly fully decayed) LR schedule
            train_state = self._maybe_restore(train_state, params_only=True)
        rollout_states = {
            m: self.collectors[m].init_state(k, self.run_cfg.n_rollout_threads)
            for m, k in zip(self.train_maps, k_rolls)
        }
        self._log_model_stats(train_state)
        return train_state, rollout_states

    def train_loop(self, num_episodes: Optional[int] = None, train_state=None,
                   rollout_states=None):
        run = self.run_cfg
        episodes = num_episodes if num_episodes is not None else run.episodes
        if train_state is None:
            train_state, rollout_states = self.setup()
        key = jax.random.key(run.seed + 7919)

        wins = {m: [] for m in self.train_maps}
        for episode in range(episodes):
            # round-robin across maps (smac_multi_runner trains each map's
            # chunk in turn); one map per outer iteration
            m = self.train_maps[episode % len(self.train_maps)]
            rollout_states[m], traj = self._collects[m](train_state.params, rollout_states[m])
            key, k_train = jax.random.split(key)
            train_state, metrics = self._train(train_state, traj, rollout_states[m], k_train)

            dones = np.asarray(traj.dones)
            won = np.asarray(traj.delays)
            # per-episode win bookkeeping: a win flag fires on terminal steps
            if dones.any():
                wins[m].extend(won[dones].tolist())

            if episode % run.log_interval == 0:
                record = {
                    "episode": episode,
                    "map": m,
                    "average_step_rewards": float(np.asarray(traj.rewards).mean()),
                    "value_loss": float(np.mean(metrics.value_loss)),
                    "policy_loss": float(np.mean(metrics.policy_loss)),
                    "dist_entropy": float(np.mean(metrics.dist_entropy)),
                }
                for name, w in wins.items():
                    if w:
                        record[f"win_rate_{name}"] = float(np.mean(w))
                wins = {m_: [] for m_ in self.train_maps}
                self.writer.write(record, step=episode)
                self.log(f"ep {episode} [{m}] {record}")

            if episode % run.save_interval == 0 or episode == episodes - 1:
                self.ckpt.save(episode, train_state)
        return train_state, rollout_states

    def evaluate(self, train_state, maps: Optional[Sequence[str]] = None,
                 n_episodes: int = 16, seed: int = 0):
        """Per-map deterministic win rates; ``maps`` may include held-out maps
        (few-shot eval, ``smac_multi_runner.py:160-275``)."""
        maps = tuple(maps) if maps is not None else self.train_maps
        out = {}
        for m in maps:
            env = self.envs.get(m) or self._make_env(m)
            collector = RolloutCollector(env, self.policy, self.run_cfg.episode_length)
            sub = SMACRunner.__new__(SMACRunner)       # reuse the eval loop only
            sub.run_cfg = self.run_cfg
            sub.policy = self.policy
            sub.collector = collector
            sub.is_mat = True                          # multi-map is MAT-only
            info = SMACRunner.evaluate(sub, train_state, n_episodes=n_episodes, seed=seed)
            out[f"eval_win_rate_{m}"] = info["eval_win_rate"]
        return out


class SMACScenarioRunner(SMACRunner):
    """One policy over a same-roster map family via scenario-as-data
    (``envs/scenario.py``): the map is a per-slot parameter leaf in the
    rollout carry, resampled on episode reset inside the jitted step, so a
    single compiled program covers the whole roster and the fused
    ``--iters_per_dispatch`` dispatch applies unchanged — unlike
    :class:`SMACMultiRunner`'s host cycle, which compiles one program per
    map and trains them round-robin from Python."""

    def __init__(self, run: RunConfig, ppo: PPOConfig,
                 train_maps: Sequence[str],
                 weights: Optional[Sequence[float]] = None, log_fn=print):
        if run.algorithm_name not in ("mat", "mat_dec"):
            raise NotImplementedError(
                "scenario-as-data multi-map training drives the MAT family"
            )
        self.train_maps = tuple(train_maps)
        self._eval_roll = None
        base_env, sset = build_smac_scenario_set(self.train_maps, weights)
        super().__init__(run, ppo, ScenarioEnv(base_env, sset, SMACScenarioFamily),
                         log_fn=log_fn)

    def evaluate(self, train_state, maps: Optional[Sequence[str]] = None,
                 n_episodes: int = 16, seed: int = 0):
        """Per-map deterministic win-rate matrix: each map's scenario id is
        pinned on a resampling-frozen view, so the SMAC win flag (the delay
        info channel) attributes cleanly per map.  One jitted rollout with a
        traced scenario id serves every map — N maps = N calls into ONE
        compile.  Held-out maps are out of scope here: the policy's scenario
        one-hot has no slot for them (use ``SMACMultiRunner`` for few-shot)."""
        import numpy as np

        names = self.env.scenarios.names
        maps = tuple(maps) if maps is not None else names
        skipped = [m for m in maps if m not in names]
        if skipped:
            self.log(f"[smac-scenario] skipping out-of-roster eval maps "
                     f"{skipped} (no scenario one-hot slot; few-shot eval "
                     f"needs the host-cycled SMACMultiRunner)")
        maps = [m for m in maps if m in names]

        if self._eval_roll is None:
            senv = self.env.frozen_view()
            E = self.run_cfg.n_rollout_threads
            policy = self.policy
            # enough steps for n_episodes battles at the longest limit in
            # the roster, mirroring SMACRunner's eval-until-N budget
            limit = int(np.asarray(self.env.scenarios.params.limit).max())
            T = 2 * limit * (max(n_episodes // E, 1) + 1)

            def roll(params, sid):
                keys = jax.random.split(jax.random.key(seed + 17), E)
                states, ts = jax.vmap(senv.reset_pinned, in_axes=(0, None))(keys, sid)

                def body(carry, _):
                    states, obs, share_obs, avail = carry
                    out = policy.get_actions(
                        params, jax.random.key(0), share_obs, obs, avail,
                        deterministic=True,
                    )
                    states, ts = jax.vmap(senv.step)(states, out.action)
                    done_env = ts.done.all(axis=1)
                    stats = jnp.stack([
                        done_env.astype(jnp.float32).sum(),
                        jnp.where(done_env, ts.delay, 0.0).sum(),    # wins
                        jnp.where(done_env, ts.payment, 0.0).sum(),  # dead ratio
                        ts.reward.mean(),
                    ])
                    return (states, ts.obs, ts.share_obs,
                            ts.available_actions), stats

                carry = (states, ts.obs, ts.share_obs, ts.available_actions)
                _, stats = jax.lax.scan(body, carry, None, length=T)
                totals = stats.sum(axis=0)
                return totals[0], totals[1], totals[2], stats[:, 3].mean()

            self._eval_roll = jax.jit(roll)

        out = {"scenario_count": float(len(maps))}
        win_rates, rewards = [], []
        for m in maps:
            sid = jnp.asarray(names.index(m), jnp.int32)
            eps, wins, dead, rew = self._eval_roll(train_state.params, sid)
            eps = float(eps)
            wr = float(wins) / max(eps, 1.0)
            out[f"eval_win_rate_{m}"] = wr
            out[f"scenario_{m}_win_rate"] = wr
            out[f"scenario_{m}_dead_ratio"] = float(dead) / max(eps, 1.0)
            out[f"scenario_{m}_episodes"] = eps
            win_rates.append(wr)
            rewards.append(float(rew))
        if win_rates:
            out["eval_win_rate"] = float(np.mean(win_rates))
            out["eval_average_step_rewards"] = float(np.mean(rewards))
        return out


def make_multi_map_runner(run: RunConfig, ppo: PPOConfig,
                          train_maps: Sequence[str], random_order: bool = False,
                          log_fn=print):
    """Pick the multi-map training backend for a map roster.

    Same-shape rosters (equal ally/enemy counts and map size) compile to ONE
    program via :class:`SMACScenarioRunner`; heterogeneous rosters — or
    per-episode agent shuffling, which the scenario wrapper doesn't model —
    keep the host-cycled :class:`SMACMultiRunner` fallback."""
    maps = tuple(train_maps)
    mps = [get_map_params(m) for m in maps]
    same_shape = (
        len({(len(mp.agents), len(mp.enemies)) for mp in mps}) == 1
        and len({mp.map_size for mp in mps}) == 1
    )
    if same_shape and not random_order and len(maps) > 1:
        log_fn(f"[smac-multi] same-shape roster {maps}: scenario-as-data path")
        return SMACScenarioRunner(run, ppo, maps, log_fn=log_fn)
    if len(maps) > 1:
        why = "random_order" if random_order else "heterogeneous roster"
        log_fn(f"[smac-multi] {why}: host-cycled fallback over {maps}")
    return SMACMultiRunner(run, ppo, maps, random_order=random_order,
                           log_fn=log_fn)
