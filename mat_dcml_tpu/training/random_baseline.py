"""Random baseline policy + no-op trainer.

Reference: ``mat/algorithms/random/`` — ``random_policy.py:79-109`` samples,
per agent, a uniform-random *available* discrete action for the first
``n_agent + semi_index`` agents and ``uniform(0, 1)`` for the continuous tail
(the DCML coding-ratio agent); values and log-probs are zeros and the trainer
is a scaffold whose ``train`` does nothing.  Used as the sanity anchor the
benchmark sweeps compare against (SURVEY.md §4.2).

The reference's double Python loop over (thread, agent) is one masked-gumbel
draw here: sampling uniformly among available actions == argmax of
``U ~ Gumbel`` restricted to the available set.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RandomPolicyOutput(NamedTuple):
    value: jax.Array
    action: jax.Array
    log_prob: jax.Array


class RandomTrainState(NamedTuple):
    """Matches the ``TrainState.params`` attribute the runner reads."""

    params: dict


class RandomPolicy:
    """Drop-in for ``TransformerPolicy.get_actions`` on the DCML layout.

    ``n_cont_tail`` agents at the end of the agent axis emit U(0, 1) scalars
    (the coding ratio); all others pick uniformly among available discrete
    actions.  Stateless: ``params`` is an empty dict for API compatibility.
    """

    def __init__(self, n_agent: int, action_dim: int, n_cont_tail: int = 1):
        self.n_agent = n_agent
        self.action_dim = action_dim
        self.n_cont_tail = n_cont_tail

    def init_params(self, key: jax.Array):
        del key
        return {}

    def get_actions(self, params, key: jax.Array, share_obs, obs, available_actions,
                    deterministic: bool = False) -> RandomPolicyOutput:
        """(B, A, ...) batched sampling.  ``deterministic`` is ignored — the
        reference has no deterministic random mode."""
        del params, share_obs, deterministic
        B, A = obs.shape[:2]
        k_disc, k_cont = jax.random.split(key)

        ava = available_actions if available_actions is not None else jnp.ones(
            (B, A, self.action_dim)
        )
        # uniform over the available set: masked Gumbel-max
        g = jax.random.gumbel(k_disc, (B, A, self.action_dim))
        disc = jnp.argmax(jnp.where(ava > 0, g, -jnp.inf), axis=-1).astype(jnp.float32)

        cont = jax.random.uniform(k_cont, (B, A))
        is_tail = jnp.arange(A) >= (A - self.n_cont_tail)
        action = jnp.where(is_tail[None, :], cont, disc)[..., None]

        zeros = jnp.zeros((B, A, 1), jnp.float32)
        return RandomPolicyOutput(value=zeros, action=action, log_prob=zeros)


class RandomTrainer:
    """No-op trainer scaffold (``random_trainer.py``): keeps the runner's
    collect→train loop shape without learning anything.  Metrics match the
    ``TrainMetrics`` attribute contract the runner logs from."""

    def __init__(self, policy: RandomPolicy):
        self.policy = policy

    def init_state(self, params):
        return RandomTrainState(params=params)

    def train(self, state, traj=None, *args, **kwargs):
        from mat_dcml_tpu.training.ppo import TrainMetrics

        z = jnp.zeros(())
        return state, TrainMetrics(
            value_loss=z, policy_loss=z, dist_entropy=z, grad_norm=z, ratio=jnp.ones(())
        )
