"""V-trace-style truncated importance-sampling correction for stale async
trajectory blocks (arXiv:1802.01561; the seam async_loop's
``ImportanceCorrection`` hook contract reserves).

With ``--staleness_budget B > 1`` the learner consumes blocks collected under
params up to B publishes old: the stored ``traj.log_probs`` are the BEHAVIOR
policy's, while the PPO update's ratio is taken against them as if they were
current.  The correction re-evaluates the trajectory's actions under the
CURRENT learner params (the target policy) and attaches the raw per-timestep
importance ratio

    rho_t = pi_target(a_t | s_t) / pi_behavior(a_t | s_t)
          = exp(sum_dims(logp_target - logp_behavior))

as ``traj.is_weights``; the PPO/MAPPO loss truncates it per V-trace —
``min(rho, rho_bar)`` on the policy surrogate, ``min(rho, c_bar)`` on the
value loss (``PPOConfig.vtrace_rho_bar`` / ``vtrace_c_bar``).  Keeping the
RAW ratio on the trajectory and clipping inside the loss keeps the hook free
of trainer hyperparameters and makes the attached weights reusable by both
trainer families.

Structure stability: the hook is applied by the learner to EVERY consumed
block while a correction is enabled — at ``lag == 0`` the target and
behavior params coincide and rho == 1 exactly (a numerical identity), but
the ``is_weights`` leaf is always present, so the jitted update's input
pytree structure never flips mid-run and the zero-steady-state-recompile
guarantee survives.  When the correction is disabled the leaf is always
None.  The hook runs on the learner thread BEFORE the (donating) train step
reads the same params, which device-stream ordering serializes — no use-
after-donate.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from mat_dcml_tpu.telemetry import Telemetry


def truncated_is_weights(logp_target: jax.Array, logp_behavior: jax.Array,
                         clip: Optional[float] = None) -> jax.Array:
    """Raw (or ``clip``-truncated) per-timestep joint importance ratio.

    ``logp_*`` are per-action-dim log-probs ``(..., act_prob)``; the joint
    ratio is the product over dims = ``exp(sum(delta))``, shape ``(..., 1)``.
    Pinned against a hand-computed example in tests/test_off_policy.py.
    """
    rho = jnp.exp((logp_target - logp_behavior).sum(-1, keepdims=True))
    if clip is not None:
        rho = jnp.minimum(rho, clip)
    return rho


def _rho_stats(rho: jax.Array, rho_bar: float, c_bar: float):
    """Scalar summaries for the ``offpolicy_`` gauge family."""
    return {
        "rho_mean": rho.mean(),
        "rho_max": rho.max(),
        "rho_clip_fraction": (rho > rho_bar).mean(),
        "c_clip_fraction": (rho > c_bar).mean(),
    }


def make_vtrace_correction(policy, params_fn: Callable[[], dict],
                           rho_bar: float = 1.0, c_bar: float = 1.0,
                           telemetry: Optional[Telemetry] = None):
    """Build the ``hook(traj, lag) -> traj`` for the MAT family.

    ``policy`` is the TransformerPolicy whose ``evaluate_actions`` scores the
    stored actions; ``params_fn`` returns the CURRENT learner params at call
    time (a closure over the training loop's ``train_state`` rebinds — the
    hook always sees the newest published version).  ``rho_bar`` / ``c_bar``
    only feed the clip-fraction gauges here; the loss applies the actual
    truncation.  The scoring program is jitted once and reused — stable
    shapes mean exactly one compile per run.
    """

    def _raw_rho(params, share_obs, obs, actions, available_actions,
                 log_probs):
        T, E = obs.shape[:2]

        def rows(x):
            return x.reshape(T * E, *x.shape[2:])

        _, logp, _ = policy.evaluate_actions(
            params, rows(share_obs), rows(obs), rows(actions),
            rows(available_actions),
        )
        logp = logp.reshape(T, E, *logp.shape[1:])
        rho = truncated_is_weights(logp, log_probs)
        return rho, _rho_stats(rho, rho_bar, c_bar)

    score_jit = jax.jit(_raw_rho)

    def hook(traj, lag: int):
        rho, stats = score_jit(
            params_fn(), traj.share_obs, traj.obs, traj.actions,
            traj.available_actions, traj.log_probs,
        )
        if telemetry is not None:
            telemetry.count("offpolicy_applied")
            telemetry.gauge("offpolicy_lag", float(lag))
            for k, v in stats.items():
                telemetry.gauge(f"offpolicy_{k}", float(v))
        return traj._replace(is_weights=rho)

    return hook


def make_ac_vtrace_correction(policy, params_fn: Callable[[], dict],
                              rho_bar: float = 1.0, c_bar: float = 1.0,
                              telemetry: Optional[Telemetry] = None):
    """:func:`make_vtrace_correction` for the actor-critic families
    (MAPPO/IPPO/HAPPO): scores stored actions through the AC
    ``evaluate_actions`` (per-row stored hiddens re-run each step, so the
    per-step log-probs are exact for recurrent policies too)."""

    def _raw_rho(params, traj):
        T, E = traj.obs.shape[:2]

        def rows(x):
            return x.reshape(T * E, *x.shape[2:])

        _, logp, _ = policy.evaluate_actions(
            params, rows(traj.share_obs), rows(traj.obs), rows(traj.actor_h),
            rows(traj.critic_h), rows(traj.actions), rows(traj.masks[:-1]),
            rows(traj.available_actions), rows(traj.active_masks[:-1]),
        )
        logp = logp.reshape(T, E, *logp.shape[1:])
        rho = truncated_is_weights(logp, traj.log_probs)
        return rho, _rho_stats(rho, rho_bar, c_bar)

    score_jit = jax.jit(_raw_rho)

    def hook(traj, lag: int):
        rho, stats = score_jit(params_fn(), traj)
        if telemetry is not None:
            telemetry.count("offpolicy_applied")
            telemetry.gauge("offpolicy_lag", float(lag))
            for k, v in stats.items():
                telemetry.gauge(f"offpolicy_{k}", float(v))
        return traj._replace(is_weights=rho)

    return hook


def resolve_correction_mode(mode: str, staleness_budget: int) -> bool:
    """``--off_policy_correction`` -> is V-trace on?  "auto" enables it
    exactly when stale blocks are admissible (B > 1), so B = 1 runs stay
    bit-exact with the PR 13 uncorrected path."""
    if mode not in ("auto", "vtrace", "none"):
        raise ValueError(
            f"--off_policy_correction must be auto|vtrace|none, got {mode!r}"
        )
    if mode == "auto":
        return staleness_budget > 1
    return mode == "vtrace"
