"""Football runner: MAT over the host-process bridge with score metrics.

``runner/shared/football_runner.py``: the collect/train loop over host
gfootball workers, logging goal-difference "scores".  The env emits per-step
score deltas on the generic episode-info channel, so the shared runner
accounting's per-episode sums ARE the goal difference — this runner just
renames them.  Architecture: jitted MAT policy + HostRolloutCollector over
ShareSubprocVecEnv/ShareDummyVecEnv (``envs/vec_env.py``), the pattern every
non-JAX env family uses.
"""

from __future__ import annotations

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.vec_env import ShareVecEnv
from mat_dcml_tpu.training.base_runner import BaseRunner
from mat_dcml_tpu.training.generic_runner import build_discrete_policy
from mat_dcml_tpu.training.host_rollout import HostRolloutCollector
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig


class FootballRunner(BaseRunner):
    def __init__(self, run: RunConfig, ppo: PPOConfig, vec_env: ShareVecEnv,
                 log_fn=print):
        if run.algorithm_name not in ("mat", "mat_dec"):
            raise NotImplementedError(
                "the football runner drives the MAT family (football_runner.py)"
            )
        if run.n_rollout_threads != vec_env.n_envs:
            raise ValueError(
                f"n_rollout_threads={run.n_rollout_threads} != vec env size {vec_env.n_envs}"
            )
        self.env = vec_env
        self.is_mat = True
        self.policy = build_discrete_policy(run, vec_env)
        self.trainer = MATTrainer(self.policy, ppo, total_updates=run.episodes)
        self.collector = HostRolloutCollector(vec_env, self.policy, run.episode_length)
        self.finalize(run, log_fn)

    def _extra_metrics(self, record: dict) -> None:
        if "aver_episode_delays" in record:
            record["scores"] = record.pop("aver_episode_delays")   # goal diff
            record.pop("aver_episode_payments", None)
