"""Trajectory collection over host-process envs (the non-JAX escape hatch).

Twin of :class:`~mat_dcml_tpu.training.rollout.RolloutCollector` for envs
behind a :class:`~mat_dcml_tpu.envs.vec_env.ShareVecEnv`: the policy runs as
one jitted call per step on the full ``(E, A, ·)`` batch, actions cross to the
host once, the worker processes step their envs in lock-step, and the stacked
transition crosses back once — the reference's rollout round trip
(``env_wrappers.py:367-379`` + ``dcml_runner.py:145-248``) with the
per-process pickling replaced by two bulk host↔device transfers per step.

Produces the same :class:`Trajectory` pytree as the scan-based collector, so
``MATTrainer`` (and anything else consuming trajectories) is oblivious to
where the envs live.  PRNG discipline matches the scan collector exactly
(split the carried key once per step for the policy), so a JAX env driven
through :class:`JaxEnvHostAdapter` yields bit-identical rollouts — the bridge
correctness test.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.envs.vec_env import ShareVecEnv
from mat_dcml_tpu.training.rollout import RolloutState, Trajectory


def _info_field(info, name: str) -> float:
    """Pull a scalar info channel from a per-env info of any common shape:
    the reference's list-of-per-agent-dicts (``DCML_Basic_Env.py:9-17``), a
    plain dict, or nothing."""
    if isinstance(info, dict):
        return float(info.get(name, 0.0))
    if isinstance(info, (list, tuple)) and info and isinstance(info[0], dict):
        return float(info[0].get(name, 0.0))
    return 0.0


class HostRolloutCollector:
    """Builds ``collect`` for a (policy, host vec-env) pair."""

    jittable = False          # the collect loop crosses the host boundary

    def __init__(self, vec_env: ShareVecEnv, policy, episode_length: int):
        self.vec_env = vec_env
        self.policy = policy
        self.T = episode_length
        n_objective = getattr(getattr(policy, "cfg", None), "n_objective", 1)
        if n_objective != 1:
            raise NotImplementedError(
                "multi-objective rollouts need per-channel rewards, which the "
                "host env contract does not carry; MO/DMO-MAT run on pure-JAX "
                "envs via RolloutCollector"
            )

        def _act(params, key, share_obs, obs, avail):
            return self.policy.get_actions(
                params, key, share_obs, obs, avail, deterministic=False
            )

        self._act = jax.jit(_act)

    def init_state(self, key: jax.Array, n_envs: int = 0) -> RolloutState:
        """``n_envs`` is fixed by the vec env; the arg mirrors the scan
        collector's signature so runners can treat both alike."""
        if n_envs and n_envs != self.vec_env.n_envs:
            raise ValueError(
                f"vec env has {self.vec_env.n_envs} envs, runner asked for {n_envs}"
            )
        obs, share, avail = self.vec_env.reset()
        E, A = obs.shape[:2]
        return RolloutState(
            env_states=None,                       # env state lives in workers
            obs=jnp.asarray(obs, jnp.float32),
            share_obs=jnp.asarray(share, jnp.float32),
            available_actions=jnp.asarray(avail, jnp.float32),
            mask=jnp.ones((E, A, 1), jnp.float32),
            rng=key,
        )

    def collect(self, params, st: RolloutState) -> Tuple[RolloutState, Trajectory]:
        E = self.vec_env.n_envs
        tr: dict = {k: [] for k in (
            "share_obs", "obs", "available_actions", "actions", "log_probs",
            "values", "rewards", "next_mask", "delay", "payment", "done",
        )}
        obs, share, avail, mask, key = st.obs, st.share_obs, st.available_actions, st.mask, st.rng

        for _ in range(self.T):
            key, k_act = jax.random.split(key)
            out = self._act(params, k_act, share, obs, avail)
            tr["share_obs"].append(share)
            tr["obs"].append(obs)
            tr["available_actions"].append(avail)
            tr["actions"].append(out.action)
            tr["log_probs"].append(out.log_prob)
            tr["values"].append(out.value)

            obs_np, share_np, rew, done, infos, avail_np = self.vec_env.step(
                np.asarray(out.action)
            )
            done_env = np.asarray(done).all(axis=1)              # (E,)
            next_mask = np.broadcast_to(
                np.where(done_env[:, None, None], 0.0, 1.0), mask.shape
            ).astype(np.float32)
            tr["rewards"].append(np.asarray(rew, np.float32))
            tr["next_mask"].append(next_mask)
            tr["delay"].append([_info_field(i, "delay") for i in infos])
            tr["payment"].append([_info_field(i, "payment") for i in infos])
            tr["done"].append(done_env)

            obs = jnp.asarray(obs_np, jnp.float32)
            share = jnp.asarray(share_np, jnp.float32)
            avail = jnp.asarray(avail_np, jnp.float32)
            mask = jnp.asarray(next_mask)

        new_st = RolloutState(
            env_states=None, obs=obs, share_obs=share, available_actions=avail,
            mask=mask, rng=key,
        )
        stack = lambda xs: jnp.stack([jnp.asarray(x) for x in xs])
        masks = jnp.concatenate([st.mask[None], stack(tr["next_mask"])], axis=0)
        traj = Trajectory(
            share_obs=stack(tr["share_obs"]),
            obs=stack(tr["obs"]),
            available_actions=stack(tr["available_actions"]),
            actions=stack(tr["actions"]),
            log_probs=stack(tr["log_probs"]),
            values=stack(tr["values"]),
            rewards=stack(tr["rewards"]),
            masks=masks,
            active_masks=jnp.ones_like(masks),
            delays=jnp.asarray(np.asarray(tr["delay"], np.float32)),
            payments=jnp.asarray(np.asarray(tr["payment"], np.float32)),
            dones=jnp.asarray(np.asarray(tr["done"])),
        )
        return new_st, traj
