"""Independent PPO: per-agent parameters, decentralized value functions.

Reference: ``ippo/ippo_policy.py`` + ``ippo/ippo_trainer.py`` — one policy
(actor + critic on *local* obs) per agent, each trained on its own slice of
the shared rollout via separated buffers (``base_runner.py:120-140``).

TPU-native shape: agent parameters are stacked along a leading axis and the
whole MAPPO update is ``vmap``-ped over it — the reference's Python loop over
``trainer[agent].train(buffer[agent])`` becomes one batched program.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.actor_critic import ActorCriticPolicy
from mat_dcml_tpu.training.ac_rollout import ACTrajectory
from mat_dcml_tpu.training.mappo import (
    Bootstrap,
    MAPPOConfig,
    MAPPOMetrics,
    MAPPOTrainer,
    MAPPOTrainState,
)


class IPPORolloutCollector:
    """Rollout collection with *per-agent* stacked params: each agent's own
    actor/critic act on its slice, the reference's per-agent policy list
    (``base_runner.py:120-140``) collapsed into one vmapped apply.

    IPPO is decentralized-V: the critic consumes local obs
    (``ippo_policy.py:13-29``), so ``share_obs`` stored in the trajectory is
    the local obs too.
    """

    def __init__(self, env, policy: ActorCriticPolicy, episode_length: int):
        self.env = env
        self.policy = policy
        self.T = episode_length
        self.use_local_value = True

    def init_state(self, key: jax.Array, n_envs: int):
        from mat_dcml_tpu.training.ac_rollout import ACRolloutCollector

        return ACRolloutCollector(self.env, self.policy, self.T, True).init_state(key, n_envs)

    def collect(self, stacked_params, rs):
        from mat_dcml_tpu.training.ac_rollout import ACRolloutState, ACTrajectory

        pol = self.policy

        def body(st: ACRolloutState, _):
            key, k_act = jax.random.split(st.rng)
            A = st.obs.shape[1]
            keys = jax.random.split(k_act, A)
            out = jax.vmap(pol.get_actions, in_axes=(0, 0, 1, 1, 1, 1, 1, 1), out_axes=1)(
                stacked_params, keys, st.obs, st.obs, st.actor_h, st.critic_h,
                st.mask, st.available_actions,
            )
            env_states, ts = jax.vmap(self.env.step)(st.env_states, out.action)
            done_env = ts.done.all(axis=1)
            next_mask = jnp.broadcast_to(
                jnp.where(done_env[:, None, None], 0.0, 1.0), st.mask.shape
            )
            tr = dict(
                share_obs=st.obs, obs=st.obs,
                available_actions=st.available_actions,
                actions=out.action, log_probs=out.log_prob, values=out.value,
                rewards=ts.reward, next_mask=next_mask,
                actor_h=st.actor_h, critic_h=st.critic_h, done=done_env,
            )
            new_st = st._replace(
                env_states=env_states, obs=ts.obs, share_obs=ts.share_obs,
                available_actions=ts.available_actions, mask=next_mask,
                actor_h=out.actor_h, critic_h=out.critic_h, rng=key,
            )
            return new_st, tr

        final, tr = jax.lax.scan(body, rs, None, length=self.T)
        masks = jnp.concatenate([rs.mask[None], tr["next_mask"]], axis=0)
        traj = ACTrajectory(
            share_obs=tr["share_obs"], obs=tr["obs"],
            available_actions=tr["available_actions"], actions=tr["actions"],
            log_probs=tr["log_probs"], values=tr["values"], rewards=tr["rewards"],
            masks=masks, active_masks=jnp.ones_like(masks),
            actor_h=tr["actor_h"], critic_h=tr["critic_h"], dones=tr["done"],
        )
        return final, traj


class IPPOTrainer:
    """vmapped per-agent MAPPO.  ``policy`` is the *single-agent* template;
    params/opt-state pytrees carry a leading agent axis."""

    def __init__(self, policy: ActorCriticPolicy, cfg: MAPPOConfig, n_agents: int):
        # IPPO importance weights use the prod convention (ippo_trainer.py:128).
        self.inner = MAPPOTrainer(policy, cfg)
        self.n_agents = n_agents

    def init_params(self, key: jax.Array):
        keys = jax.random.split(key, self.n_agents)
        return jax.vmap(self.inner.policy.init_params)(keys)

    def init_state(self, stacked_params) -> MAPPOTrainState:
        return jax.vmap(self.inner.init_state)(stacked_params)

    def train(self, state: MAPPOTrainState, traj: ACTrajectory, boot: Bootstrap,
              key: jax.Array) -> Tuple[MAPPOTrainState, MAPPOMetrics]:
        A = traj.rewards.shape[2]
        assert A == self.n_agents

        def slice_traj(x):
            # (T, E, A, ...) -> (A, T, E, 1, ...): agent axis first, singleton
            # kept so the inner single-policy trainer sees its 4D layout.
            return jnp.moveaxis(x, 2, 0)[:, :, :, None]

        traj_a = ACTrajectory(
            share_obs=slice_traj(traj.share_obs),
            obs=slice_traj(traj.obs),
            available_actions=slice_traj(traj.available_actions),
            actions=slice_traj(traj.actions),
            log_probs=slice_traj(traj.log_probs),
            values=slice_traj(traj.values),
            rewards=slice_traj(traj.rewards),
            masks=slice_traj(traj.masks),
            active_masks=slice_traj(traj.active_masks),
            actor_h=slice_traj(traj.actor_h),
            critic_h=slice_traj(traj.critic_h),
            dones=jnp.broadcast_to(traj.dones, (A, *traj.dones.shape)),
        )
        boot_a = Bootstrap(
            cent_obs=jnp.moveaxis(boot.cent_obs, 1, 0)[:, :, None],
            critic_h=jnp.moveaxis(boot.critic_h, 1, 0)[:, :, None],
            mask=jnp.moveaxis(boot.mask, 1, 0)[:, :, None],
        )
        keys = jax.random.split(key, A)
        return jax.vmap(self.inner.train)(state, traj_a, boot_a, keys)
