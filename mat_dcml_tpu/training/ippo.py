"""Independent PPO: per-agent parameters, decentralized value functions.

Reference: ``ippo/ippo_policy.py`` + ``ippo/ippo_trainer.py`` — one policy
(actor + critic on *local* obs) per agent, each trained on its own slice of
the shared rollout via separated buffers (``base_runner.py:120-140``).

TPU-native shape: agent parameters are stacked along a leading axis and the
whole MAPPO update is ``vmap``-ped over it — the reference's Python loop over
``trainer[agent].train(buffer[agent])`` becomes one batched program.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.actor_critic import ActorCriticPolicy
from mat_dcml_tpu.training.ac_rollout import ACRolloutCollector, ACTrajectory
from mat_dcml_tpu.training.mappo import (
    Bootstrap,
    MAPPOConfig,
    MAPPOMetrics,
    MAPPOTrainer,
    MAPPOTrainState,
    ac_train_iteration,
)
from mat_dcml_tpu.telemetry.scopes import probe


class IPPORolloutCollector(ACRolloutCollector):
    """Rollout collection with *per-agent* stacked params: each agent's own
    actor/critic act on its slice, the reference's per-agent policy list
    (``base_runner.py:120-140``) collapsed into one vmapped apply.

    IPPO is decentralized-V: the critic consumes local obs
    (``ippo_policy.py:13-29``), so ``share_obs`` stored in the trajectory is
    the local obs too.  ``use_local_value=False`` gives the HAPPO/HATRPO
    configuration: per-agent params but a centralized critic over
    ``share_obs`` (``happo_policy.py`` critic input).
    """

    def __init__(self, env, policy: ActorCriticPolicy, episode_length: int,
                 use_local_value: bool = True):
        super().__init__(env, policy, episode_length, use_local_value)

    def _apply(self, stacked_params, key, st, deterministic: bool = False):
        A = st.obs.shape[1]
        keys = jax.random.split(key, A)

        def one(p, k, cent, obs, ah, ch, m, av):
            return self.policy.get_actions(p, k, cent, obs, ah, ch, m, av, deterministic)

        return jax.vmap(one, in_axes=(0, 0, 1, 1, 1, 1, 1, 1), out_axes=1)(
            stacked_params, keys, self._cent(st), st.obs, st.actor_h,
            st.critic_h, st.mask, st.available_actions,
        )


class IPPOTrainer:
    """vmapped per-agent MAPPO.  ``policy`` is the *single-agent* template;
    params/opt-state pytrees carry a leading agent axis."""

    def __init__(self, policy: ActorCriticPolicy, cfg: MAPPOConfig, n_agents: int):
        # IPPO importance weights use the prod convention (ippo_trainer.py:128);
        # enforced here rather than trusted to the caller.
        import dataclasses

        self.inner = MAPPOTrainer(policy, dataclasses.replace(cfg, importance_prod=True))
        self.n_agents = n_agents

    def init_params(self, key: jax.Array):
        keys = jax.random.split(key, self.n_agents)
        return jax.vmap(self.inner.policy.init_params)(keys)

    def init_state(self, stacked_params) -> MAPPOTrainState:
        return jax.vmap(self.inner.init_state)(stacked_params)

    def train_iteration(self, collector, state: MAPPOTrainState, rollout_state,
                        key: jax.Array):
        """Fused collect+train unit for ``--iters_per_dispatch`` (see
        :func:`mat_dcml_tpu.training.mappo.ac_train_iteration`)."""
        return ac_train_iteration(self, collector, state, rollout_state, key)

    def train(self, state: MAPPOTrainState, traj: ACTrajectory, boot: Bootstrap,
              key: jax.Array) -> Tuple[MAPPOTrainState, MAPPOMetrics]:
        A = traj.rewards.shape[2]
        assert A == self.n_agents

        def slice_traj(x):
            # (T, E, A, ...) -> (A, T, E, 1, ...): agent axis first, singleton
            # kept so the inner single-policy trainer sees its 4D layout.
            return jnp.moveaxis(x, 2, 0)[:, :, :, None]

        traj_a = ACTrajectory(
            share_obs=slice_traj(traj.share_obs),
            obs=slice_traj(traj.obs),
            available_actions=slice_traj(traj.available_actions),
            actions=slice_traj(traj.actions),
            log_probs=slice_traj(traj.log_probs),
            values=slice_traj(traj.values),
            rewards=slice_traj(traj.rewards),
            masks=slice_traj(traj.masks),
            active_masks=slice_traj(traj.active_masks),
            actor_h=slice_traj(traj.actor_h),
            critic_h=slice_traj(traj.critic_h),
            dones=jnp.broadcast_to(traj.dones, (A, *traj.dones.shape)),
        )
        boot_a = Bootstrap(
            cent_obs=jnp.moveaxis(boot.cent_obs, 1, 0)[:, :, None],
            critic_h=jnp.moveaxis(boot.critic_h, 1, 0)[:, :, None],
            mask=jnp.moveaxis(boot.mask, 1, 0)[:, :, None],
        )
        keys = jax.random.split(key, A)
        state, metrics = jax.vmap(self.inner.train)(state, traj_a, boot_a, keys)
        probe("train/ippo_update", {"grad_norm": metrics.grad_norm})
        return state, metrics
