"""Training stack: on-device rollouts, PPO trainers, runners."""

from mat_dcml_tpu.training.ppo import PPOConfig, TrainState, MATTrainer
from mat_dcml_tpu.training.rollout import Trajectory, RolloutCollector
