"""DCML training orchestration (the reference's ``dcml_runner.py``).

Algorithm dispatch covers the reference's DCML branches
(``dcml_runner.py:145-248``: mat / momat / ppo / happo / random) plus the
families the library supports beyond them (dmomat, mappo/rmappo, ippo,
hatrpo).  The collect/train loop, checkpoint restore/resume, and metric
accounting live in :class:`~mat_dcml_tpu.training.base_runner.BaseRunner`;
this module adds the DCML-specific policy/trainer construction and the
deterministic eval protocol with episode delay/payment accounting and
inference timing (``dcml_runner.py:319-448``).

With a mesh (``--data_shards`` / ``--seq_shards``, parallel/mesh
.build_run_mesh), the same jitted functions — the two-dispatch loop AND the
fused ``--iters_per_dispatch`` scan — run with the env batch sharded over the
``data`` axis: state is built as global arrays (params replicated via
jit-with-out_shardings, rollout state via parallel.distributed
.global_init_state), and the grad psums and batch-statistic reductions fall
out of jit.  Everything else is unchanged (SURVEY.md §7.6).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.dcml import DCMLEnv, DCMLEnvConfig
from mat_dcml_tpu.envs.dcml.joint import JointDCMLEnv
from mat_dcml_tpu.envs.dcml.per_agent import PerAgentDCMLEnv
from mat_dcml_tpu.models.actor_critic import ACConfig, ActorCriticPolicy
from mat_dcml_tpu.models.mat import MATConfig, SEMI_DISCRETE
from mat_dcml_tpu.models.policy import TransformerPolicy
from mat_dcml_tpu.training.ac_rollout import ACRolloutCollector, ACRolloutState
from mat_dcml_tpu.training.base_runner import BaseRunner, ac_config_kwargs, apply_mesh
from mat_dcml_tpu.training.happo import (
    HAPPOConfig,
    HAPPORolloutCollector,
    HAPPOTrainer,
    HATRPOTrainer,
)
from mat_dcml_tpu.training.ippo import IPPORolloutCollector, IPPOTrainer
from mat_dcml_tpu.training.mappo import MAPPOConfig, MAPPOTrainer
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig
from mat_dcml_tpu.training.rollout import RolloutCollector, RolloutState


MAT_DCML_ALGOS = ("mat", "mat_dec", "momat", "dmomat")
AC_DCML_ALGOS = ("ppo", "mappo", "rmappo", "ippo", "happo", "hatrpo",
                 "rhappo", "rhatrpo")
SUPPORTED_DCML_ALGOS = MAT_DCML_ALGOS + AC_DCML_ALGOS + ("random",)


def build_mat_policy(run: RunConfig, env: DCMLEnv) -> TransformerPolicy:
    if run.algorithm_name not in MAT_DCML_ALGOS:
        # The encoder/decoder/GRU ablations are discrete/continuous-only, as
        # upstream (mat_encoder.py:183-196 has no Semi_Discrete branch);
        # DCML's semi-discrete action layout needs the full MAT.  Erroring
        # beats silently training vanilla MAT under an ablation's run label.
        raise NotImplementedError(
            f"algorithm_name={run.algorithm_name!r} is not a MAT-family DCML "
            f"algorithm; MAT family: {MAT_DCML_ALGOS}. "
            "mat_encoder/mat_decoder/mat_gru run on discrete/continuous envs "
            "via mat_dcml_tpu.models.mat_variants."
        )
    n_objective = 2 if run.algorithm_name in ("momat", "dmomat") else run.n_objective
    # dmomat conditions the policy on the per-episode preference weights: the
    # collector appends them to BOTH obs and share_obs (the encoder reads obs
    # unless encode_state, ma_transformer.py:144-149)
    widen = n_objective if run.algorithm_name == "dmomat" else 0
    cfg = MATConfig(
        n_agent=env.n_agents,
        obs_dim=env.obs_dim + widen,
        state_dim=env.share_obs_dim + widen,
        action_dim=env.action_dim,
        n_block=run.n_block,
        n_embd=run.n_embd,
        n_head=run.n_head,
        dtype=run.model_dtype,
        remat=run.remat,
        action_type=SEMI_DISCRETE,
        semi_index=-env.cfg.consts.extra_agent if hasattr(env, "cfg") else -1,
        encode_state=run.encode_state,
        dec_actor=run.dec_actor or run.algorithm_name == "mat_dec",
        share_actor=run.share_actor or run.algorithm_name == "mat_dec",
        # momat/dmomat: vector-valued critic over (completion-time, payment)
        # channels — the reconstructed MO-MAT (SURVEY.md §2.4 missing modules)
        n_objective=n_objective,
    )
    if run.decode_mode == "spec" and cfg.dec_actor:
        # spec_decode needs the shared autoregressive decoder: the dec_actor
        # ablation's per-agent MLPs have no KV-cache/draft structure to verify.
        raise ValueError(
            "decode_mode='spec' is incompatible with dec_actor/mat_dec; "
            "use decode_mode='scan'"
        )
    if run.decode_mode == "stride":
        # stride is the deterministic benchmark-protocol decode (evaluate()'s
        # stride= arg); it cannot sample, so it cannot collect rollouts.
        raise ValueError(
            "decode_mode='stride' is eval-only (see DCMLRunner.evaluate); "
            "training collect needs 'cached', 'scan', or 'spec'"
        )
    return TransformerPolicy(cfg, decode_mode=run.decode_mode, spec_block=run.spec_block)


def build_dcml_components(run: RunConfig, ppo: PPOConfig, env: DCMLEnv):
    """Construct ``(policy, trainer, collector, is_mat)`` for a DCML run.

    Shared by :class:`DCMLRunner` and ``scripts/replay_bundle.py`` — the
    replay path must rebuild the exact same jittable functions from a bundle
    manifest without triggering the runner's finalize side effects (writers,
    telemetry, checkpoint restore).
    """
    if run.algorithm_name not in SUPPORTED_DCML_ALGOS:
        raise NotImplementedError(
            f"algorithm_name={run.algorithm_name!r}; supported on DCML: "
            f"{SUPPORTED_DCML_ALGOS}"
        )
    algo = run.algorithm_name

    if algo == "random":
        # uniform-random-valid-actions sanity anchor (random_policy.py:79-109)
        from mat_dcml_tpu.training.random_baseline import RandomPolicy, RandomTrainer

        policy = RandomPolicy(env.n_agents, env.action_dim)
        trainer = RandomTrainer(policy)
        collector = RolloutCollector(env, policy, run.episode_length)
    elif algo in MAT_DCML_ALGOS:
        policy = build_mat_policy(run, env)
        trainer = MATTrainer(policy, ppo, total_updates=run.episodes)
        collector = RolloutCollector(
            env,
            policy,
            run.episode_length,
            dynamic_coefficients=algo == "dmomat",
        )
    else:
        mcfg_kwargs = ac_config_kwargs(ppo)
        use_rec = algo in ("rmappo", "rhappo", "rhatrpo")
        ac = ACConfig(
            hidden_size=run.n_embd,
            use_recurrent_policy=use_rec,
        )
        if algo == "ppo":
            # centralized PPO over the joint action (ppo_policy.py +
            # SingleReplayBuffer): one agent, mixed action space, prod
            # importance weights (ppo_trainer.py:128)
            wrapped = JointDCMLEnv(env)
            policy = ActorCriticPolicy(
                ac, obs_dim=wrapped.obs_dim, cent_obs_dim=wrapped.share_obs_dim,
                space=wrapped.action_space,
            )
            trainer = MAPPOTrainer(
                policy, MAPPOConfig(importance_prod=True, **mcfg_kwargs)
            )
            collector = ACRolloutCollector(wrapped, policy, run.episode_length)
        else:
            wrapped = PerAgentDCMLEnv(env)
            policy = ActorCriticPolicy(
                ac,
                obs_dim=wrapped.obs_dim,
                cent_obs_dim=wrapped.obs_dim if algo == "ippo" else wrapped.share_obs_dim,
                space=wrapped.action_space,
            )
            if algo in ("mappo", "rmappo"):
                trainer = MAPPOTrainer(policy, MAPPOConfig(
                    use_recurrent_policy=algo == "rmappo", **mcfg_kwargs))
                collector = ACRolloutCollector(wrapped, policy, run.episode_length)
            elif algo == "ippo":
                trainer = IPPOTrainer(
                    policy, MAPPOConfig(**mcfg_kwargs), n_agents=wrapped.n_agents
                )
                collector = IPPORolloutCollector(
                    wrapped, policy, run.episode_length, use_local_value=True
                )
            else:  # happo / hatrpo (r* = recurrent chunked variants)
                trainer_cls = HATRPOTrainer if algo.endswith("hatrpo") else HAPPOTrainer
                trainer = trainer_cls(
                    policy,
                    HAPPOConfig(use_recurrent_policy=use_rec, **mcfg_kwargs),
                    n_agents=wrapped.n_agents,
                )
                collector = HAPPORolloutCollector(wrapped, policy, run.episode_length)

    is_mat = algo in MAT_DCML_ALGOS or algo == "random"
    return policy, trainer, collector, is_mat


class DCMLRunner(BaseRunner):
    """Rollout-train loop with episode metric accounting
    (``dcml_runner.py:22-124``)."""

    def __init__(
        self,
        run: RunConfig,
        ppo: PPOConfig,
        env: Optional[DCMLEnv] = None,
        data_dir: str = "data",
        log_fn=print,
    ):
        self.ppo_cfg = ppo
        self.env = env if env is not None else DCMLEnv(DCMLEnvConfig(), data_dir=data_dir)
        self.policy, self.trainer, self.collector, self.is_mat = (
            build_dcml_components(run, ppo, self.env)
        )
        self.mesh = apply_mesh(run, self.policy)
        self.finalize(run, log_fn)

    # ----------------------------------------------------------------- eval

    def evaluate(self, train_state, n_steps: int = 100, seed: int = 0, stride: Optional[int] = None):
        """Deterministic-policy eval on fresh envs with episode delay/payment
        accounting and per-call inference timing (``dcml_runner.py:319-448``).
        ``stride`` switches MAT to the reference's block-commit decode."""
        E = self.run_cfg.n_rollout_threads
        rollout_state = self.collector.init_state(jax.random.key(seed + 13), E)
        env = self.collector.env

        if self.is_mat:
            if stride is None:
                def act(params, st, key):
                    out = self.policy.get_actions(
                        params, key, st.share_obs, st.obs, st.available_actions,
                        deterministic=True,
                    )
                    return out.action
            else:
                def act(params, st, key):
                    out = self.policy.act_stride(
                        params, st.share_obs, st.obs, st.available_actions, stride=stride
                    )
                    return out.action

            def step(st: RolloutState, action):
                env_states, ts = jax.vmap(env.step)(st.env_states, action)
                coefs = st.objective_coefficients
                new_st = RolloutState(
                    env_states,
                    self.collector.augment_share_obs(ts.obs, coefs),
                    self.collector.augment_share_obs(ts.share_obs, coefs),
                    ts.available_actions, st.mask, st.rng,
                    objective_coefficients=coefs,
                )
                rew_env = ts.reward.sum(-1).mean(-1)           # (E,) per-env
                return new_st, (rew_env, ts.delay, ts.payment, ts.done)
        else:
            def act(params, st, key):
                return self.collector.apply(params, key, st, deterministic=True)

            def step(st: ACRolloutState, out):
                env_states, ts = jax.vmap(env.step)(st.env_states, out.action)
                done_env = ts.done.all(axis=1)
                mask = jnp.broadcast_to(
                    jnp.where(done_env[:, None, None], jnp.float32(0.0), jnp.float32(1.0)),
                    st.mask.shape,
                )
                new_st = ACRolloutState(
                    env_states, ts.obs, ts.share_obs, ts.available_actions,
                    mask, out.actor_h, out.critic_h, st.rng,
                )
                rew_env = ts.reward.sum(-1).mean(-1)           # (E,) per-env
                return new_st, (rew_env, ts.delay, ts.payment, ts.done)

        act_j = jax.jit(act)
        step_j = jax.jit(step)

        # warm up compiles so inference timing measures steady-state latency
        # (the reference times each policy call, dcml_runner.py:337-400)
        _ = jax.block_until_ready(act_j(train_state.params, rollout_state, jax.random.key(0)))

        rewards, delays, payments = [], [], []
        acc_delay, acc_pay, acc_rew = np.zeros(E), np.zeros(E), np.zeros(E)
        ep_delays, ep_payments, ep_rewards = [], [], []
        infer_time = 0.0
        for i in range(n_steps):
            t0 = time.perf_counter()
            action = jax.block_until_ready(
                act_j(train_state.params, rollout_state, jax.random.key(i))
            )
            infer_time += time.perf_counter() - t0
            rollout_state, (r, d, p, done) = step_j(rollout_state, action)
            r, d, p, done = np.asarray(r), np.asarray(d), np.asarray(p), np.asarray(done)
            done_env = done.all(axis=1) if done.ndim > 1 else done
            rewards.append(float(r.mean()))
            delays.append(float(d.mean()))
            payments.append(float(p.mean()))
            acc_rew += r
            acc_delay += d
            acc_pay += p
            if done_env.any():
                ep_rewards.extend(acc_rew[done_env].tolist())
                ep_delays.extend(acc_delay[done_env].tolist())
                ep_payments.extend(acc_pay[done_env].tolist())
                acc_rew[done_env] = 0
                acc_delay[done_env] = 0
                acc_pay[done_env] = 0

        info = {
            "eval_average_step_rewards": float(np.mean(rewards)),
            "eval_average_delays": float(np.mean(delays)),
            "eval_average_payments": float(np.mean(payments)),
            "eval_inference_sec_per_call": infer_time / n_steps,
        }
        if ep_delays:
            info["eval_aver_episode_rewards"] = float(np.mean(ep_rewards))
            info["eval_aver_episode_delays"] = float(np.mean(ep_delays))
            info["eval_aver_episode_payments"] = float(np.mean(ep_payments))
        return info
