"""Training orchestration for DCML (the L6 "runner" layer).

Replaces ``dcml_runner.py`` + ``base_runner.py``: the collect / insert /
compute / train phases collapse into two jitted calls per episode chunk —
``collect`` (rollout scan) and ``train`` (PPO update) — with host-side code
left for logging, episode accounting, and checkpointing only.

With a mesh, the same two functions are jitted with the env batch sharded over
the ``data`` axis; everything else is unchanged (SURVEY.md §7.6).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mat_dcml_tpu.config import RunConfig
from mat_dcml_tpu.envs.dcml import DCMLEnv, DCMLEnvConfig
from mat_dcml_tpu.models.mat import MATConfig, SEMI_DISCRETE
from mat_dcml_tpu.models.policy import TransformerPolicy
from mat_dcml_tpu.training.checkpoint import CheckpointManager
from mat_dcml_tpu.training.ppo import MATTrainer, PPOConfig, TrainState
from mat_dcml_tpu.training.rollout import RolloutCollector, RolloutState


SUPPORTED_DCML_ALGOS = ("mat", "mat_dec", "momat", "dmomat", "random")


def build_mat_policy(run: RunConfig, env: DCMLEnv) -> TransformerPolicy:
    if run.algorithm_name not in SUPPORTED_DCML_ALGOS:
        # The encoder/decoder/GRU ablations are discrete/continuous-only, as
        # upstream (mat_encoder.py:183-196 has no Semi_Discrete branch);
        # DCML's semi-discrete action layout needs the full MAT.  Erroring
        # beats silently training vanilla MAT under an ablation's run label.
        raise NotImplementedError(
            f"algorithm_name={run.algorithm_name!r} is not wired for the DCML "
            f"(semi-discrete) runner yet; supported: {SUPPORTED_DCML_ALGOS}. "
            "mat_encoder/mat_decoder/mat_gru run on discrete/continuous envs "
            "via mat_dcml_tpu.models.mat_variants."
        )
    n_objective = 2 if run.algorithm_name in ("momat", "dmomat") else run.n_objective
    # dmomat conditions the policy on the per-episode preference weights: the
    # collector appends them to BOTH obs and share_obs (the encoder reads obs
    # unless encode_state, ma_transformer.py:144-149)
    widen = n_objective if run.algorithm_name == "dmomat" else 0
    cfg = MATConfig(
        n_agent=env.n_agents,
        obs_dim=env.obs_dim + widen,
        state_dim=env.share_obs_dim + widen,
        action_dim=env.action_dim,
        n_block=run.n_block,
        n_embd=run.n_embd,
        n_head=run.n_head,
        action_type=SEMI_DISCRETE,
        semi_index=-env.cfg.consts.extra_agent if hasattr(env, "cfg") else -1,
        encode_state=run.encode_state,
        dec_actor=run.dec_actor or run.algorithm_name == "mat_dec",
        share_actor=run.share_actor or run.algorithm_name == "mat_dec",
        # momat/dmomat: vector-valued critic over (completion-time, payment)
        # channels — the reconstructed MO-MAT (SURVEY.md §2.4 missing modules)
        n_objective=n_objective,
    )
    return TransformerPolicy(cfg)


class DCMLRunner:
    """Rollout-train loop with episode metric accounting
    (``dcml_runner.py:22-124``)."""

    def __init__(
        self,
        run: RunConfig,
        ppo: PPOConfig,
        env: Optional[DCMLEnv] = None,
        data_dir: str = "data",
        log_fn=print,
    ):
        self.run_cfg = run
        self.ppo_cfg = ppo
        self.log = log_fn
        self.env = env if env is not None else DCMLEnv(DCMLEnvConfig(), data_dir=data_dir)
        if run.algorithm_name == "random":
            # uniform-random-valid-actions sanity anchor (random_policy.py:79-109)
            from mat_dcml_tpu.training.random_baseline import RandomPolicy, RandomTrainer

            self.policy = RandomPolicy(self.env.n_agents, self.env.action_dim)
            self.trainer = RandomTrainer(self.policy)
        else:
            self.policy = build_mat_policy(run, self.env)
            self.trainer = MATTrainer(self.policy, ppo, total_updates=run.episodes)
        self.collector = RolloutCollector(
            self.env,
            self.policy,
            run.episode_length,
            dynamic_coefficients=run.algorithm_name == "dmomat",
        )

        self._collect = jax.jit(self.collector.collect)
        self._train = jax.jit(self.trainer.train)

        self.run_dir = Path(run.run_dir) / run.env_name / run.scenario / run.algorithm_name / run.experiment_name
        self.ckpt = CheckpointManager(self.run_dir / "models")
        self.metrics_path = self.run_dir / "metrics.jsonl"

    def setup(self, seed: Optional[int] = None):
        seed = self.run_cfg.seed if seed is None else seed
        key = jax.random.key(seed)
        k_model, k_roll = jax.random.split(key)
        params = self.policy.init_params(k_model)
        train_state = self.trainer.init_state(params)
        rollout_state = self.collector.init_state(k_roll, self.run_cfg.n_rollout_threads)
        return train_state, rollout_state

    def train_loop(self, num_episodes: Optional[int] = None, train_state=None, rollout_state=None):
        run = self.run_cfg
        episodes = num_episodes if num_episodes is not None else run.episodes
        if train_state is None:
            train_state, rollout_state = self.setup()
        key = jax.random.key(run.seed + 7919)

        # episode accounting (dcml_runner.py:29-74)
        E = run.n_rollout_threads
        acc_rew = np.zeros(E)
        acc_delay = np.zeros(E)
        acc_pay = np.zeros(E)
        done_rewards, done_delays, done_payments = [], [], []

        start = time.time()
        for episode in range(episodes):
            rollout_state, traj = self._collect(train_state.params, rollout_state)
            key, k_train = jax.random.split(key)
            train_state, metrics = self._train(train_state, traj, rollout_state, k_train)

            # host-side episode metric accumulation (one device->host copy)
            rew_arr = np.asarray(traj.rewards)                 # (T, E, A, n_obj)
            # sum objective channels (== scalar reward), mean over agents
            rew = rew_arr.sum(axis=3).mean(axis=2)             # (T, E)
            delays = np.asarray(traj.delays)
            pays = np.asarray(traj.payments)
            dones = np.asarray(traj.dones)
            for t in range(rew.shape[0]):
                acc_rew += rew[t]
                acc_delay += delays[t]
                acc_pay += pays[t]
                finished = dones[t]
                if finished.any():
                    done_rewards.extend(acc_rew[finished].tolist())
                    done_delays.extend(acc_delay[finished].tolist())
                    done_payments.extend(acc_pay[finished].tolist())
                    acc_rew[finished] = 0
                    acc_delay[finished] = 0
                    acc_pay[finished] = 0

            total_steps = (episode + 1) * run.episode_length * E
            if episode % run.log_interval == 0:
                elapsed = time.time() - start
                fps = total_steps / max(elapsed, 1e-9)
                record = {
                    "episode": episode,
                    "total_steps": total_steps,
                    "fps": fps,
                    "average_step_rewards": float(rew_arr.sum(-1).mean()),
                    "value_loss": float(metrics.value_loss),
                    "policy_loss": float(metrics.policy_loss),
                    "dist_entropy": float(metrics.dist_entropy),
                    "grad_norm": float(metrics.grad_norm),
                    "ratio": float(metrics.ratio),
                }
                if rew_arr.shape[-1] > 1:
                    # per-objective channel means (dcml_runner.py:306-309)
                    for i in range(rew_arr.shape[-1]):
                        record[f"average_step_objective_{i}"] = float(rew_arr[..., i].mean())
                if done_rewards:
                    record["aver_episode_rewards"] = float(np.mean(done_rewards))
                    record["aver_episode_delays"] = float(np.mean(done_delays))
                    record["aver_episode_payments"] = float(np.mean(done_payments))
                    done_rewards, done_delays, done_payments = [], [], []
                self._log_record(record)

            if (episode % run.save_interval == 0 or episode == episodes - 1) and run.algorithm_name != "random":
                self.ckpt.save(episode, train_state)

            if run.use_eval and episode % run.eval_interval == 0:
                eval_info = self.evaluate(train_state, n_steps=run.episode_length)
                eval_info.update(episode=episode, total_steps=total_steps)
                self.metrics_path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.metrics_path, "a") as f:
                    f.write(json.dumps(eval_info) + "\n")
                self.log(f"eval ep {episode}: {eval_info}")

        return train_state, rollout_state

    def _log_record(self, record: dict):
        self.metrics_path.parent.mkdir(parents=True, exist_ok=True)
        with open(self.metrics_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        self.log(
            f"ep {record['episode']} steps {record['total_steps']} fps {record['fps']:.0f} "
            f"avg_r {record['average_step_rewards']:.3f} vloss {record['value_loss']:.3f} "
            f"ploss {record['policy_loss']:.3f} ent {record['dist_entropy']:.3f}"
        )

    # ----------------------------------------------------------------- eval

    def evaluate(self, train_state: TrainState, n_steps: int = 100, seed: int = 0, stride: Optional[int] = None):
        """Deterministic-policy eval on fresh envs (``dcml_runner.py:319-448``).
        ``stride`` switches to the reference's block-commit decode."""
        E = self.run_cfg.n_rollout_threads
        rollout_state = self.collector.init_state(jax.random.key(seed + 13), E)

        if stride is None:
            def act(params, st):
                out = self.policy.get_actions(
                    params, jax.random.key(0), st.share_obs, st.obs, st.available_actions, deterministic=True
                )
                return out.action
        else:
            def act(params, st):
                out = self.policy.act_stride(params, st.share_obs, st.obs, st.available_actions, stride=stride)
                return out.action

        @jax.jit
        def eval_step(params, st: RolloutState):
            action = act(params, st)
            env_states, ts = jax.vmap(self.env.step)(st.env_states, action)
            coefs = st.objective_coefficients
            new_st = RolloutState(
                env_states,
                self.collector.augment_share_obs(ts.obs, coefs),
                self.collector.augment_share_obs(ts.share_obs, coefs),
                ts.available_actions, st.mask, st.rng,
                objective_coefficients=coefs,
            )
            return new_st, (ts.reward.mean(), ts.delay.mean(), ts.payment.mean())

        rewards, delays, payments = [], [], []
        for _ in range(n_steps):
            rollout_state, (r, d, p) = eval_step(train_state.params, rollout_state)
            rewards.append(float(r))
            delays.append(float(d))
            payments.append(float(p))
        return {
            "eval_average_step_rewards": float(np.mean(rewards)),
            "eval_average_delays": float(np.mean(delays)),
            "eval_average_payments": float(np.mean(payments)),
        }
