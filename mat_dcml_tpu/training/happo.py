"""HAPPO / HATRPO: heterogeneous-agent trust-region families.

Reference: ``mat/algorithm/happo_policy.py`` + ``mat/happo_trainer.py`` and
``hatrpo/hatrpo_policy.py`` + ``hatrpo/hatrpo_trainer.py``, orchestrated by
the sequential-update loop in ``runner/shared/base_runner.py:329-417``:

    for agent in randperm(A):
        old_logp  = eval agent's rollout actions (no grad)
        train agent (PPO surrogate x `factor`, or a TRPO step)
        new_logp  = eval again with the updated params
        factor   *= prod(exp(new_logp - old_logp))        # :413

so later agents see earlier agents' policy shift — the advantage-decomposition
correction that MAT's decoder replaces architecturally.

TPU-native shape: agent parameters are stacked along a leading axis; the
inherently-serial agent loop is a ``lax.scan`` over a permuted index vector,
updating one agent's slice of the stacked pytree per step.  Everything jits.

Recurrent variants (``rhappo``/``rhatrpo``) follow the reference's chunked
recurrent generator (``separated_buffer.py:320-430``): ``data_chunk_length``
windows are the minibatch items, the GRU re-runs each window from the stored
chunk-start hidden, and the sequential ``factor`` is computed by re-running
the FULL episode from the t=0 hidden — matching the reference, which passes
``rnn_states[0:1]`` and lets the torch RNN layer unroll all T steps
(``base_runner.py:335-413``).

HATRPO's actor step (``hatrpo_trainer.py:125-349``) is the classic natural
gradient: CG-solve ``F x = g`` with Fisher-vector products (Hessian of the
self-KL, damping 0.1), step size ``1/sqrt(sᵀFs / 2δ)``-style scaling to the
``kl_threshold`` ball, then a backtracking line search accepting the first
fraction with ``kl < δ``, positive surrogate improvement, and improvement /
expected-improvement > ``accept_ratio``.  The torch loop with early ``break``
becomes a vmapped candidate sweep + first-accept select.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree

from mat_dcml_tpu.envs.spaces import Box
from mat_dcml_tpu.models.actor_critic import ActorCriticPolicy
from mat_dcml_tpu.telemetry.scopes import named_scope, probe
from mat_dcml_tpu.training.ac_rollout import ACTrajectory
from mat_dcml_tpu.training.ippo import IPPORolloutCollector
from mat_dcml_tpu.training.mappo import (
    Bootstrap,
    MAPPOConfig,
    MAPPOTrainer,
    MAPPOTrainState,
    ac_train_iteration,
    chunk_start_states,
    chunk_windows,
)
from mat_dcml_tpu.training.minibatch import permute_rows, slice_rows


class HAPPORolloutCollector(IPPORolloutCollector):
    """Per-agent stacked params + centralized critic (``happo_policy.py``)."""

    def __init__(self, env, policy: ActorCriticPolicy, episode_length: int):
        super().__init__(env, policy, episode_length, use_local_value=False)


@dataclasses.dataclass(frozen=True)
class HAPPOConfig(MAPPOConfig):
    """Adds the TRPO knobs (``config.py`` trpo group defaults)."""

    kl_threshold: float = 0.01
    ls_step: int = 10
    accept_ratio: float = 0.5
    cg_iters: int = 10
    cg_damping: float = 0.1


class HAPPOMetrics(NamedTuple):
    value_loss: jax.Array
    policy_loss: jax.Array
    dist_entropy: jax.Array
    ratio: jax.Array
    factor_mean: jax.Array
    kl: jax.Array            # HATRPO only; 0 for HAPPO
    accepted: jax.Array      # HATRPO line-search acceptance rate; 1 for HAPPO
    # training-health telemetry (see ppo.TrainMetrics)
    grad_norm: jax.Array = 0.0
    param_norm: jax.Array = 0.0
    update_ratio: jax.Array = 0.0
    nonfinite_grads: jax.Array = 0.0


def _rows(x: jax.Array) -> jax.Array:
    """(T, E, 1, ...) agent slice -> (T*E, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[3:])


class HAPPOTrainer:
    """Sequential-factor PPO over per-agent stacked params.

    ``policy`` is the single-agent template; ``params``/optimizer/value-norm
    pytrees carry a leading agent axis (like ``IPPOTrainer``), but training is
    a *sequential* scan over a permuted agent order with the compounding
    ``factor``, not a parallel vmap.
    """

    def __init__(self, policy: ActorCriticPolicy, cfg: HAPPOConfig, n_agents: int):
        self.policy = policy
        self.cfg = cfg
        self.n_agents = n_agents
        # HAPPO importance weights take the product over action dims
        # (happo_trainer.py:125); reuse the MAPPO helpers with that convention.
        self.inner = MAPPOTrainer(
            policy, dataclasses.replace(cfg, importance_prod=True)
        )

    # ------------------------------------------------------------------ state

    def init_params(self, key: jax.Array):
        keys = jax.random.split(key, self.n_agents)
        return jax.vmap(self.policy.init_params)(keys)

    def init_state(self, stacked_params) -> MAPPOTrainState:
        return jax.vmap(self.inner.init_state)(stacked_params)

    # ------------------------------------------------------------------ train

    def train_iteration(self, collector, state: MAPPOTrainState, rollout_state,
                        key: jax.Array):
        """Fused collect+train unit for ``--iters_per_dispatch`` (see
        :func:`mat_dcml_tpu.training.mappo.ac_train_iteration`).  HATRPO
        inherits this unchanged — its ``train`` has the same signature."""
        return ac_train_iteration(self, collector, state, rollout_state, key)

    def train(self, state: MAPPOTrainState, traj: ACTrajectory, boot: Bootstrap,
              key: jax.Array) -> Tuple[MAPPOTrainState, HAPPOMetrics]:
        A = traj.rewards.shape[2]
        assert A == self.n_agents
        T, E = traj.rewards.shape[:2]
        k_perm, k_train = jax.random.split(key)

        def slice_traj(x):
            return jnp.moveaxis(x, 2, 0)[:, :, :, None]

        traj_a = ACTrajectory(
            share_obs=slice_traj(traj.share_obs),
            obs=slice_traj(traj.obs),
            available_actions=slice_traj(traj.available_actions),
            actions=slice_traj(traj.actions),
            log_probs=slice_traj(traj.log_probs),
            values=slice_traj(traj.values),
            rewards=slice_traj(traj.rewards),
            masks=slice_traj(traj.masks),
            active_masks=slice_traj(traj.active_masks),
            actor_h=slice_traj(traj.actor_h),
            critic_h=slice_traj(traj.critic_h),
            dones=jnp.broadcast_to(traj.dones, (A, *traj.dones.shape)),
        )
        boot_a = Bootstrap(
            cent_obs=jnp.moveaxis(boot.cent_obs, 1, 0)[:, :, None],
            critic_h=jnp.moveaxis(boot.critic_h, 1, 0)[:, :, None],
            mask=jnp.moveaxis(boot.mask, 1, 0)[:, :, None],
        )
        # Per-agent GAE + advantage normalization from each agent's own critic
        # (separated buffers, ``base_runner.py:336-344``).
        adv_a, ret_a = jax.vmap(self.inner._compute_targets)(state, traj_a, boot_a)

        order = jax.random.permutation(k_perm, A)  # randperm (:334)
        agent_keys = jax.random.split(k_train, A)

        use_rec = self.cfg.use_recurrent_policy
        L = self.cfg.data_chunk_length
        if use_rec:
            assert T % L == 0, (
                f"episode_length {T} must be divisible by data_chunk_length {L}"
            )

        def one_agent(carry, inp):
            params_s, aopt_s, copt_s, vn_s, factor = carry
            idx, k_agent = inp
            take = lambda t: jax.tree.map(lambda x: x[idx], t)
            params_i, aopt_i, copt_i, vn_i = (
                take(params_s), take(aopt_s), take(copt_s), take(vn_s)
            )
            sq = lambda x: x[idx][:, :, 0]            # agent slice -> (T', E, ...)
            if use_rec:
                # the reference's recurrent generator semantics
                # (separated_buffer.py:320-430): data_chunk_length windows as
                # minibatch items, GRU re-run from stored chunk-start hiddens
                to_chunks = lambda x: chunk_windows(x, L, n_batch=1)
                starts = lambda x: chunk_start_states(x, L, n_batch=1)
                data = {
                    "cent_obs": to_chunks(sq(traj_a.share_obs)),
                    "obs": to_chunks(sq(traj_a.obs)),
                    "avail": to_chunks(sq(traj_a.available_actions)),
                    "actions": to_chunks(sq(traj_a.actions)),
                    "log_probs": to_chunks(sq(traj_a.log_probs)),
                    "values": to_chunks(sq(traj_a.values)),
                    "masks": to_chunks(sq(traj_a.masks)[:-1]),
                    "active": to_chunks(sq(traj_a.active_masks)[:-1]),
                    "actor_h0": starts(sq(traj_a.actor_h)),
                    "critic_h0": starts(sq(traj_a.critic_h)),
                    "adv": to_chunks(adv_a[idx][:, :, 0]),
                    "returns": to_chunks(ret_a[idx][:, :, 0]),
                    "factor": to_chunks(factor),
                }
                # factor evaluation re-runs the FULL episode from the t=0
                # hidden — the reference passes rnn_states[0:1] and lets the
                # torch RNN layer unroll all T steps (base_runner.py:335-413)
                seqd = {
                    "obs": sq(traj_a.obs),
                    "actions": sq(traj_a.actions),
                    "masks": sq(traj_a.masks)[:-1],
                    "avail": sq(traj_a.available_actions),
                    "active": sq(traj_a.active_masks)[:-1],
                    "h0": sq(traj_a.actor_h)[0],
                }
                eval_logp = lambda p: self._eval_logp_seq(p, seqd)  # (T, E, ad)
            else:
                data = {
                    "cent_obs": _rows(traj_a.share_obs[idx]),
                    "obs": _rows(traj_a.obs[idx]),
                    "avail": _rows(traj_a.available_actions[idx]),
                    "actions": _rows(traj_a.actions[idx]),
                    "log_probs": _rows(traj_a.log_probs[idx]),
                    "values": _rows(traj_a.values[idx]),
                    "masks": _rows(traj_a.masks[idx][:-1]),
                    "active": _rows(traj_a.active_masks[idx][:-1]),
                    "actor_h": _rows(traj_a.actor_h[idx]),
                    "critic_h": _rows(traj_a.critic_h[idx]),
                    "adv": _rows(adv_a[idx]),
                    "returns": _rows(ret_a[idx]),
                    "factor": factor.reshape(T * E, 1),
                }
                eval_logp = lambda p: self._eval_logp(p, data).reshape(T, E, -1)
            old_logp = eval_logp(params_i)
            params_i, aopt_i, copt_i, vn_i, metrics = self._update_agent(
                params_i, aopt_i, copt_i, vn_i, data, k_agent
            )
            probe("train/happo_update",
                  {"grad_norm": metrics.grad_norm, "factor": factor})
            new_logp = eval_logp(params_i)
            # factor update (:413): prod over action dims of the logp shift.
            factor = factor * jnp.exp((new_logp - old_logp).sum(-1, keepdims=True))

            put = lambda t, v: jax.tree.map(lambda full, new: full.at[idx].set(new), t, v)
            carry = (
                put(params_s, params_i), put(aopt_s, aopt_i),
                put(copt_s, copt_i), put(vn_s, vn_i), factor,
            )
            return carry, metrics._replace(factor_mean=factor.mean())

        factor0 = jnp.ones((T, E, 1), jnp.float32)
        carry0 = (state.params, state.actor_opt, state.critic_opt, state.value_norm, factor0)
        with named_scope("train/happo_update"):
            (params_s, aopt_s, copt_s, vn_s, _), metrics = jax.lax.scan(
                one_agent, carry0, (order, agent_keys)
            )
        new_state = MAPPOTrainState(params_s, aopt_s, copt_s, vn_s, state.update_step + 1)
        return new_state, jax.tree.map(lambda m: m.mean(), metrics)._replace(
            nonfinite_grads=metrics.nonfinite_grads.sum()
        )

    # ---------------------------------------------------------------- helpers

    def _eval_logp(self, params_i, data):
        logp, _ = self.policy.actor.apply(
            params_i["actor"], data["obs"], data["actor_h"], data["actions"],
            data["masks"], data["avail"], data["active"], method="evaluate",
        )
        return logp

    def _eval_logp_seq(self, params_i, seqd):
        """Full-episode GRU re-run from the t=0 hidden -> (T, E, adim)."""
        logp, _ = self.policy.actor.apply(
            params_i["actor"], seqd["obs"], seqd["h0"], seqd["actions"],
            seqd["masks"], seqd["avail"], seqd["active"], method="evaluate_seq",
        )
        return logp

    def _update_agent(self, params, aopt, copt, vn, data, key):
        """PPO epochs with the ``factor`` weighting (``happo_trainer.py:96-160``)."""
        cfg, inner = self.cfg, self.inner
        use_rec = cfg.use_recurrent_policy
        N = data["obs"].shape[0]                      # rows (ff) / chunks (rec)
        mb_size = N // cfg.num_mini_batch
        seq = lambda x: jnp.swapaxes(x, 0, 1)         # (mb, L, ...) -> (L, mb, ...)

        def ppo_update(carry, b):
            params, aopt, copt, vn = carry
            vn, params, ret_norm = inner._normalize_targets(vn, params, b["returns"])

            def loss_fn(p):
                if use_rec:
                    values, logp, ent = self.policy.evaluate_actions_seq(
                        p, seq(b["cent_obs"]), seq(b["obs"]),
                        b["actor_h0"], b["critic_h0"], seq(b["actions"]),
                        seq(b["masks"]), seq(b["avail"]), seq(b["active"]),
                    )
                    lp_old, adv_b, active_b, fct, val_b, ret_b = (
                        seq(b["log_probs"]), seq(b["adv"]), seq(b["active"]),
                        seq(b["factor"]), seq(b["values"]), seq(ret_norm),
                    )
                else:
                    values, logp, ent = self.policy.evaluate_actions(
                        p, b["cent_obs"], b["obs"], b["actor_h"], b["critic_h"],
                        b["actions"], b["masks"], b["avail"], b["active"],
                    )
                    lp_old, adv_b, active_b, fct, val_b, ret_b = (
                        b["log_probs"], b["adv"], b["active"],
                        b["factor"], b["values"], ret_norm,
                    )
                ratio = jnp.exp((logp - lp_old).sum(-1, keepdims=True))
                surr1 = ratio * adv_b
                surr2 = jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv_b
                # factor multiplies the clipped surrogate (happo_trainer.py:128-140)
                surr = (fct * jnp.minimum(surr1, surr2)).sum(-1, keepdims=True)
                if cfg.use_policy_active_masks:
                    policy_loss = -(surr * active_b).sum() / active_b.sum()
                else:
                    policy_loss = -surr.mean()
                value_loss = inner._value_loss(values, val_b, ret_b, active_b)
                total = policy_loss - ent * cfg.entropy_coef + value_loss * cfg.value_loss_coef
                return total, (value_loss, policy_loss, ent, ratio.mean())

            (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            params, aopt, copt, _, _, health = inner._apply_updates(params, grads, aopt, copt)
            vl, pl, ent, ratio = aux
            gn, pn, ur, nf = health
            zero = jnp.zeros(())
            return (params, aopt, copt, vn), HAPPOMetrics(
                vl, pl, ent, ratio, zero, zero, jnp.ones(()),
                grad_norm=gn, param_norm=pn, update_ratio=ur, nonfinite_grads=nf,
            )

        def epoch(carry, key_e):
            perm = jax.random.permutation(key_e, N)
            keep = mb_size * cfg.num_mini_batch
            if cfg.minibatch_layout == "contiguous":
                data_p = permute_rows(data, perm[:keep])
                step = lambda c, start: ppo_update(c, slice_rows(data_p, start, mb_size))
                xs = jnp.arange(cfg.num_mini_batch) * mb_size
            else:
                step = lambda c, mb_idx: ppo_update(c, jax.tree.map(lambda x: x[mb_idx], data))
                xs = perm[:keep].reshape(cfg.num_mini_batch, mb_size)
            return jax.lax.scan(step, carry, xs)

        keys = jax.random.split(key, cfg.ppo_epoch)
        (params, aopt, copt, vn), metrics = jax.lax.scan(epoch, (params, aopt, copt, vn), keys)
        return params, aopt, copt, vn, jax.tree.map(lambda m: m.mean(), metrics)._replace(
            nonfinite_grads=metrics.nonfinite_grads.sum()
        )


class HATRPOTrainer(HAPPOTrainer):
    """Sequential-factor TRPO: the HAPPO outer loop with the actor's PPO step
    replaced by a KL-constrained natural-gradient step
    (``hatrpo_trainer.py:183-349``).  One pass over minibatches per agent (the
    reference's ``train`` has no epoch loop — ``:351-412``)."""

    # ------------------------------------------------------------ kl machinery
    #
    # All helpers take the minibatch in EVAL layout: feedforward rows as-is,
    # or time-major ``(L, mb, ...)`` sequences + chunk-start hiddens when
    # ``use_recurrent_policy`` (built once per minibatch in ``_update_agent``).

    def _logp_fn(self, actor_params, b):
        if self.cfg.use_recurrent_policy:
            return self.policy.actor.apply(
                actor_params, b["obs"], b["actor_h0"], b["actions"], b["masks"],
                b["avail"], b["active"], method="evaluate_seq",
            )
        logp, ent = self.policy.actor.apply(
            actor_params, b["obs"], b["actor_h"], b["actions"], b["masks"],
            b["avail"], b["active"], method="evaluate",
        )
        return logp, ent

    def _dist_params(self, actor_params, b):
        if self.cfg.use_recurrent_policy:
            return self.policy.actor.apply(
                actor_params, b["obs"], b["actor_h0"], b["masks"], b["avail"],
                method="dist_params_seq",
            )
        return self.policy.actor.apply(
            actor_params, b["obs"], b["actor_h"], b["masks"], b["avail"],
            method="dist_params",
        )

    def _kl_vs(self, actor_params, old_ref, b):
        """Mean KL(old || new).  Continuous: closed-form diag-gaussian
        (``hatrpo_trainer.py:137-147``); otherwise the k3 estimator on taken
        actions ``exp(Δ) - 1 - Δ`` (``kl_approx``, ``:125-128``)."""
        if isinstance(self.policy.space, Box):
            mu_old, std_old = old_ref
            mu, std = self._dist_params(actor_params, b)
            kl = (
                jnp.log(std) - jnp.log(std_old)
                + (std_old**2 + (mu_old - mu) ** 2) / (2.0 * std**2)
                - 0.5
            ).sum(-1, keepdims=True)
        else:
            lp_old = old_ref
            lp, _ = self._logp_fn(actor_params, b)
            d = lp - lp_old
            kl = (jnp.exp(d) - 1.0 - d).sum(-1, keepdims=True)
        return kl.mean()

    def _old_ref(self, actor_params, b):
        if isinstance(self.policy.space, Box):
            mu, std = self._dist_params(actor_params, b)
            return jax.lax.stop_gradient(mu), jax.lax.stop_gradient(std)
        lp, _ = self._logp_fn(actor_params, b)
        return jax.lax.stop_gradient(lp)

    # ------------------------------------------------------------ actor step

    def _update_agent(self, params, aopt, copt, vn, data, key):
        cfg, inner = self.cfg, self.inner
        use_rec = cfg.use_recurrent_policy
        N = data["obs"].shape[0]
        mb_size = N // cfg.num_mini_batch
        seq = lambda x: jnp.swapaxes(x, 0, 1)

        def trpo_update(carry, mb):
            params, aopt, copt, vn = carry
            vn, params, ret_norm = inner._normalize_targets(vn, params, mb["returns"])
            if use_rec:
                # eval layout: time-major sequences + chunk-start hiddens
                b = {k: (v if k in ("actor_h0", "critic_h0") else seq(v))
                     for k, v in mb.items()}
                ret_norm = seq(ret_norm)
            else:
                b = mb

            # ---- critic: plain Adam on the clipped/huber value loss (:215-227)
            def critic_loss_fn(cp):
                if use_rec:
                    values = self.policy.critic.apply(
                        cp, b["cent_obs"], b["critic_h0"], b["masks"],
                        method="values_seq",
                    )
                else:
                    values, _ = self.policy.critic.apply(
                        cp, b["cent_obs"], b["critic_h"], b["masks"]
                    )
                return inner._value_loss(values, b["values"], ret_norm, b["active"]) * cfg.value_loss_coef

            vl, cgrads = jax.value_and_grad(critic_loss_fn)(params["critic"])
            c_up, copt = inner.critic_tx.update(cgrads, copt, params["critic"])
            params = {**params, "critic": optax.apply_updates(params["critic"], c_up)}

            # ---- actor: natural-gradient ascent on the factor-weighted surrogate
            flat0, unravel = ravel_pytree(params["actor"])

            def surrogate(aparams):
                logp, ent = self._logp_fn(aparams, b)
                ratio = jnp.exp((logp - b["log_probs"]).sum(-1, keepdims=True))
                surr = (ratio * b["factor"] * b["adv"]).sum(-1, keepdims=True)
                if cfg.use_policy_active_masks:
                    loss = (surr * b["active"]).sum() / b["active"].sum()
                else:
                    loss = surr.mean()
                return loss, ent

            (loss0, ent0), g_tree = jax.value_and_grad(surrogate, has_aux=True)(params["actor"])
            g = ravel_pytree(g_tree)[0]

            old_ref = self._old_ref(params["actor"], b)

            def kl_flat(flat):
                return self._kl_vs(unravel(flat), old_ref, b)

            kl_grad_fn = jax.grad(kl_flat)

            def fvp(v):
                # Hessian-vector product of the self-KL + damping (:171-181)
                hvp = jax.grad(lambda f: jnp.vdot(kl_grad_fn(f), v))(flat0)
                return hvp + cfg.cg_damping * v

            # CG solve F x = g (:151-169), fixed iteration count under jit
            def cg_body(carry, _):
                x, r, p, rdotr = carry
                Ap = fvp(p)
                alpha = rdotr / jnp.maximum(jnp.vdot(p, Ap), 1e-10)
                x = x + alpha * p
                r = r - alpha * Ap
                new_rdotr = jnp.vdot(r, r)
                beta = new_rdotr / jnp.maximum(rdotr, 1e-10)
                p = r + beta * p
                return (x, r, p, new_rdotr), None

            x0 = jnp.zeros_like(g)
            (step_dir, _, _, _), _ = jax.lax.scan(
                cg_body, (x0, g, g, jnp.vdot(g, g)), None, length=cfg.cg_iters
            )

            shs = 0.5 * jnp.vdot(step_dir, fvp(step_dir))
            step_size = 1.0 / jnp.sqrt(jnp.maximum(shs / cfg.kl_threshold, 1e-10))
            full_step = step_size * step_dir
            expected_improve = jnp.vdot(g, full_step)

            # Backtracking line search (:287-345): all ls_step fractions
            # evaluated batched, first acceptable one selected.
            fracs = 0.5 ** jnp.arange(cfg.ls_step, dtype=jnp.float32)

            def candidate(frac):
                new_flat = flat0 + frac * full_step
                new_loss, _ = surrogate(unravel(new_flat))
                improve = new_loss - loss0
                kl = kl_flat(new_flat)
                expected = expected_improve * frac
                ok = (
                    (kl < cfg.kl_threshold)
                    & (improve / jnp.where(jnp.abs(expected) < 1e-10, 1e-10, expected)
                       > cfg.accept_ratio)
                    & (improve > 0)
                )
                return ok, new_flat, kl

            oks, flats, kls = jax.vmap(candidate)(fracs)
            first = jnp.argmax(oks)
            accepted = oks.any()
            new_flat = jnp.where(accepted, flats[first], flat0)
            kl_sel = jnp.where(accepted, kls[first], 0.0)
            params = {**params, "actor": unravel(new_flat)}

            # health: critic Adam grad + actor surrogate grad combined; the
            # applied update is the critic step plus the accepted actor step
            gnorm = jnp.sqrt(optax.global_norm(cgrads) ** 2 + jnp.vdot(g, g))
            pnorm = optax.global_norm(params)
            astep = new_flat - flat0
            unorm = jnp.sqrt(optax.global_norm(c_up) ** 2 + jnp.vdot(astep, astep))
            metrics = HAPPOMetrics(
                value_loss=vl,
                policy_loss=-loss0,
                dist_entropy=ent0,
                ratio=jnp.ones(()),
                factor_mean=jnp.zeros(()),
                kl=kl_sel,
                accepted=accepted.astype(jnp.float32),
                grad_norm=gnorm,
                param_norm=pnorm,
                update_ratio=unorm / (pnorm + 1e-12),
                nonfinite_grads=(~jnp.isfinite(gnorm)).astype(jnp.float32),
            )
            return (params, aopt, copt, vn), metrics

        perm = jax.random.permutation(key, N)
        keep = mb_size * cfg.num_mini_batch
        if cfg.minibatch_layout == "contiguous":
            data_p = permute_rows(data, perm[:keep])
            step = lambda c, start: trpo_update(c, slice_rows(data_p, start, mb_size))
            xs = jnp.arange(cfg.num_mini_batch) * mb_size
        else:
            step = lambda c, mb_idx: trpo_update(c, jax.tree.map(lambda x: x[mb_idx], data))
            xs = perm[:keep].reshape(cfg.num_mini_batch, mb_size)
        (params, aopt, copt, vn), metrics = jax.lax.scan(
            step, (params, aopt, copt, vn), xs
        )
        return params, aopt, copt, vn, jax.tree.map(lambda m: m.mean(), metrics)._replace(
            nonfinite_grads=metrics.nonfinite_grads.sum()
        )
