"""Podracer-style async actor–learner overlap (sebulba, arXiv:2104.06272).

The fused dispatch (base_runner.make_dispatch_fn) time-slices ONE device set:
the learner idles while envs step and vice versa.  This module overlaps two
programs on disjoint submeshes (parallel/mesh.build_actor_learner_meshes):

- an **actor thread** runs the existing jitted rollout collector continuously
  on the actor submesh, stamping each trajectory block with the param version
  it collected under and pushing it into a bounded queue;
- the **learner** (the main thread, where signal handlers and checkpointing
  live) consumes blocks with the existing streamed PPO update on the learner
  submesh and publishes fresh params device-to-device after every step.

The queue is a host-coordinated ring of DEVICE buffers: blocks are placed
onto the learner submesh at enqueue time (``put_time_major`` /
``put_sharded_state`` device-to-device copies, overlapping the learner's
compute), so the host holds only references and ``capacity`` bounds learner
HBM.  Backpressure blocks the producer — a full queue means the learner is
the bottleneck and more rollouts would only go stale; nothing is ever
dropped (``drops`` is pinned at 0 by tests/test_async_loop.py).

Staleness semantics: the learner accepts lag-tolerant PPO (bit-exactness
with the synchronous loop is explicitly NOT a goal — convergence parity on
the DCML preset is pinned in BENCHLOG instead).  ``ParamPublisher`` versions
every publish; the lag ``publisher.version - block.param_version`` observed
at consume time feeds the ``staleness_`` gauge family.

Scale-out (``--async_actor_workers N``): N :class:`ActorWorker` threads each
own a carved slice of the actor submesh
(``parallel.mesh.carve_actor_worker_meshes``) and a private telemetry
registry, and feed one shared :class:`TrajectoryStore` — a multi-producer
generalization of :class:`TrajectoryQueue` whose admission control enforces a
**staleness budget** ``--staleness_budget B``: a worker may start collecting
only while ``tickets + depth + consuming <= B`` (tickets = collects in
flight, depth = queued blocks, consuming = the block the learner is training
on right now).  Every block admitted when S others are outstanding is
consumed after at most S subsequent publishes, so consumed lag <= B by
construction — ``B = 1`` reproduces PR 13's double-buffering throttle
(collect-during-train, steady-state lag <= 1) without the version-polling
loop.  The importance-correction hook (:data:`IMPORTANCE_CORRECTION_DOC`) is
the seam the V-trace-style truncated-IS implementation in
``training/off_policy.py`` plugs into when ``B > 1``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, NamedTuple, Optional

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.telemetry import Telemetry


class ActorDeadError(RuntimeError):
    """The actor thread is dead (no recorded error, queue still open — the
    silent mode a crashed C extension or injected chaos produces) and the
    restart budget is spent.  Raised by the learner's liveness check instead
    of blocking forever on ``TrajectoryQueue.get``."""


class TrajectoryBlock(NamedTuple):
    """One collected episode chunk in flight from actors to learner."""

    traj: Any                 # Trajectory, placed on the LEARNER submesh
    rollout_state: Any        # post-collect bootstrap state, learner submesh
    param_version: int        # publisher version the actor collected under
    actor_iter: int           # 1-based actor iteration (FIFO assertable)
    t_start: float            # perf_counter at collect launch (actor thread)
    t_end: float              # perf_counter when the block was ready
    worker_id: int = 0        # which ActorWorker produced this block


# The importance-correction hook contract: ``hook(traj, lag) -> traj`` is
# applied by the learner BEFORE the PPO update on EVERY consumed block while
# a correction is enabled (``lag`` may be 0 — the hook must be a numerical
# identity there), and never while disabled, so the trajectory pytree
# STRUCTURE seen by the jitted update is constant for the whole run and the
# steady-state recompile guarantee holds.  The default (None) is the
# identity — PPO's ratio clipping already absorbs the <=1-step lag the
# ``staleness_budget=1`` store produces.  The real implementation
# (V-trace-style truncated importance weights over ``traj.log_probs``) lives
# in ``training/off_policy.make_vtrace_correction`` and attaches raw
# behavior/target ratios as ``traj.is_weights``; the PPO/MAPPO loss clips
# them at rho-bar / c-bar.
ImportanceCorrection = Callable[[Any, int], Any]
IMPORTANCE_CORRECTION_DOC = ImportanceCorrection


class TrajectoryQueue:
    """Bounded FIFO ring of trajectory blocks with blocking backpressure.

    ``put`` blocks while the queue is at capacity (the actor stalls rather
    than dropping or overwriting data — ``drops`` exists only to pin that
    claim in tests); ``get`` blocks while it is empty.  ``close`` wakes every
    waiter; a closed queue rejects puts (``False``) and serves remaining
    blocks until ``drain`` clears them.  Plain host Python — the blocks'
    arrays live on device, the ring only coordinates.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._slots: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.puts = 0
        self.gets = 0
        self.drops = 0          # never incremented: backpressure, not loss
        self.max_depth = 0

    @property
    def depth(self) -> int:
        return len(self._slots)

    @property
    def closed(self) -> bool:
        return self._closed

    def put(self, block, timeout: Optional[float] = None) -> bool:
        """Enqueue, blocking while full.  ``False`` = closed or timed out
        (the block was NOT enqueued; a stopping producer discards it — that
        is shutdown drain, not a drop)."""
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_queue_put()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while len(self._slots) >= self.capacity and not self._closed:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            if self._closed:
                return False
            self._slots.append(block)
            self.puts += 1
            self.max_depth = max(self.max_depth, len(self._slots))
            self._on_put_locked()
            self._cv.notify_all()
            return True

    def _on_put_locked(self) -> None:
        """Subclass hook, called under ``_cv`` right after a successful
        append (TrajectoryStore converts the producer's admission ticket
        into queue depth here, atomically)."""

    def _on_get_locked(self) -> None:
        """Subclass hook, called under ``_cv`` right after a successful pop
        (TrajectoryStore marks the block as being consumed here — the same
        critical section, so admission never sees depth drop before
        ``consuming`` rises)."""

    def get(self, timeout: Optional[float] = None):
        """Dequeue FIFO, blocking while empty.  ``None`` = closed-and-empty
        or timed out."""
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_queue_get()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._slots and not self._closed:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)
            if not self._slots:
                return None          # closed and fully drained
            block = self._slots.popleft()
            self.gets += 1
            self._on_get_locked()
            self._cv.notify_all()
            return block

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self) -> list:
        """Close and return every still-queued block in FIFO order (the
        graceful-stop path: in-flight blocks are coherently discarded and the
        carry resumes from the last CONSUMED episode)."""
        with self._cv:
            self._closed = True
            left = list(self._slots)
            self._slots.clear()
            self._cv.notify_all()
            return left


class TrajectoryStore(TrajectoryQueue):
    """Multi-producer :class:`TrajectoryQueue` with staleness-budget
    admission control.

    N actor workers call :meth:`admit` before every collect; the call blocks
    while ``tickets + depth + consuming > staleness_budget`` where

    - ``tickets``   — admitted collects not yet enqueued (in flight on some
      actor submesh slice),
    - ``depth``     — completed blocks waiting in the ring,
    - ``consuming`` — 1 between the learner's :meth:`get` and its
      post-update :meth:`mark_consumed` (the train + publish window).

    A block admitted when ``S`` others are outstanding is consumed after at
    most ``S`` subsequent publishes, so the param-version lag of every
    consumed block is ``<= staleness_budget`` by construction (asserted as
    ``staleness_learner_steps_p95 <= B`` by the chaos invariants).  With
    ``B = 1`` this reduces to PR 13's double-buffering throttle: one block
    may be collected while the learner trains on another, steady-state lag
    ``<= 1``.  Note ``B < N`` serializes collection — only B workers can
    ever be admitted at once, so near-linear N-worker scaling needs
    ``B >= N`` (measured honestly in ``BENCH_ASYNC_SCALE``).

    Ticket hygiene: a successful :meth:`put` consumes the producer's ticket
    atomically; a producer that aborts (stop request, closed store, crash
    with a loud error) must :meth:`cancel_ticket`.  A SILENTLY dead actor
    cannot — the learner's liveness/restart path reclaims its ticket via
    ``ActorWorker.holding_ticket``, so an injected ``actor_crash`` never
    leaks admission capacity.  ``close`` wakes admit-waiters (they return
    ``False``), so graceful stop never deadlocks on admission.
    """

    def __init__(self, capacity: int, staleness_budget: int = 1):
        super().__init__(capacity)
        if staleness_budget < 1:
            raise ValueError(
                f"staleness budget must be >= 1, got {staleness_budget}"
            )
        self.staleness_budget = int(staleness_budget)
        self._tickets = 0
        self._consuming = 0
        self.admits = 0

    @property
    def tickets(self) -> int:
        return self._tickets

    @property
    def consuming(self) -> int:
        return self._consuming

    @property
    def outstanding(self) -> int:
        """tickets + depth + consuming — what admission gates on."""
        with self._cv:
            return self._tickets + len(self._slots) + self._consuming

    def admit(self, timeout: Optional[float] = None) -> bool:
        """Grant a collect ticket, blocking while the budget is spoken for.
        ``False`` = closed or timed out (no ticket was taken)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while (not self._closed
                   and (self._tickets + len(self._slots) + self._consuming
                        > self.staleness_budget)):
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            if self._closed:
                return False
            self._tickets += 1
            self.admits += 1
            return True

    def cancel_ticket(self) -> None:
        """Return an unused ticket (producer aborted between admit and put,
        or the learner reclaims a silently-dead worker's ticket)."""
        with self._cv:
            if self._tickets > 0:
                self._tickets -= 1
                self._cv.notify_all()

    def mark_consumed(self) -> None:
        """Learner-side: the block taken by the last :meth:`get` has been
        trained on AND the resulting params published — it no longer counts
        against the staleness budget."""
        with self._cv:
            if self._consuming > 0:
                self._consuming -= 1
                self._cv.notify_all()

    def _on_put_locked(self) -> None:
        if self._tickets > 0:
            self._tickets -= 1

    def _on_get_locked(self) -> None:
        self._consuming += 1


class ParamPublisher:
    """Versioned device-to-device param broadcast, learner -> actor submesh.

    ``publish`` places the fresh params on the actor submesh through the
    spec layer (``parallel.sharding.place_params`` — one ``device_put`` per
    leaf = direct device-to-device copy, no host staging; ``param_specs``
    default to None = replicated, and learner-side fsdp/tp-sharded inbound
    leaves reshard on the way) and bumps the version; ``snapshot`` hands the
    actor the latest (params, version) pair.  The publish blocks until the
    copy lands so the learner's next (donating) update can never invalidate
    buffers a copy still reads.

    ``actor_mesh`` may be one mesh (PR 13 single-worker shape, or None for
    mesh-free test use) or a LIST of per-worker meshes
    (``carve_actor_worker_meshes``): publish then places one copy per slice
    and ``snapshot(worker)`` hands each worker the copy on its own devices.
    Every slice is placed under one version bump — workers never observe
    torn versions.
    """

    def __init__(self, actor_mesh=None, param_specs=None):
        if actor_mesh is None:
            meshes = [None]          # single-device / test use
        elif isinstance(actor_mesh, (list, tuple)):
            meshes = list(actor_mesh) if actor_mesh else [None]
        else:
            meshes = [actor_mesh]
        self._meshes = meshes
        self._specs = param_specs
        self._lock = threading.Lock()
        self._params: Optional[list] = None
        self._version = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, params) -> int:
        import jax

        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_param_publish()
        placed = []
        for mesh in self._meshes:
            if mesh is not None:
                from mat_dcml_tpu.parallel.sharding import place_params

                copy = place_params(params, mesh, self._specs)
                jax.block_until_ready(copy)
            else:
                copy = params
            placed.append(copy)
        with self._lock:
            self._version += 1
            self._params = placed
            return self._version

    def snapshot(self, worker: int = 0):
        """Latest ``(params, version)`` for ``worker``'s submesh slice —
        what that worker's next iteration collects under."""
        with self._lock:
            if self._params is None:
                return None, self._version
            # a publisher built with fewer meshes than workers (single shared
            # actor mesh) hands everyone the one copy
            idx = worker if worker < len(self._params) else 0
            return self._params[idx], self._version


class ActorWorker(threading.Thread):
    """The actor program: collect continuously, stamp, place, enqueue.

    Owns a PRIVATE :class:`Telemetry` registry (jit instrumentation is not
    thread-safe against the learner's flushes) guarded by ``tel_lock``; the
    learner merges every worker's registry through ``TelemetryAggregator``
    into the metrics record under the ``async_actor_`` prefix, plus
    per-worker ``async_actor_w<id>_`` labelled keys.  ``latest_rollout_state``
    always references the newest completed carry — what a graceful stop packs
    after :meth:`request_stop` joins the thread at an iteration boundary.

    When ``queue`` is a :class:`TrajectoryStore`, each iteration first takes
    an admission ticket (the staleness-budget gate); against a plain
    :class:`TrajectoryQueue` the PR 13 double-buffering throttle is kept for
    back-compat.  ``holding_ticket`` is the learner-readable flag that lets
    the restart path reclaim a silently-dead worker's ticket.
    """

    def __init__(self, collect_fn, publisher: ParamPublisher,
                 queue: TrajectoryQueue, rollout_state, learner_mesh,
                 telemetry: Optional[Telemetry] = None, log=print,
                 worker_id: int = 0):
        super().__init__(name=f"async-actor-w{worker_id}", daemon=True)
        self.collect_fn = collect_fn
        self.publisher = publisher
        self.queue = queue
        self.learner_mesh = learner_mesh
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.tel_lock = threading.Lock()
        self.log = log
        self.worker_id = int(worker_id)
        self.latest_rollout_state = rollout_state
        self.iterations = 0
        self.error: Optional[BaseException] = None
        self.holding_ticket = False
        # NOT named _stop: threading.Thread has an internal _stop()
        # method that the interpreter calls on thread teardown
        self._stop_requested = threading.Event()

    def request_stop(self) -> None:
        """Ask the actor to exit at its next iteration boundary (the enqueue
        retry loop polls this, so a stop never deadlocks on a full queue)."""
        self._stop_requested.set()

    @property
    def stop_requested(self) -> bool:
        """True once a stop was asked for — the learner's liveness check
        uses this to tell an intentionally-quiesced worker from a dead one."""
        return self._stop_requested.is_set()

    def run(self) -> None:
        import jax

        from mat_dcml_tpu.parallel.distributed import (
            put_sharded_state,
            put_time_major,
        )

        rs = self.latest_rollout_state
        last_version = -1
        admit = getattr(self.queue, "admit", None)
        try:
            while not self._stop_requested.is_set():
                if _chaos.ACTIVE is not None:
                    _chaos.ACTIVE.on_actor_iteration(
                        self.iterations + 1, worker=f"w{self.worker_id}")
                if admit is not None:
                    # staleness-budget admission: block until collecting one
                    # more cannot push any consumed block past B versions
                    # stale (see TrajectoryStore).  Short timeouts keep the
                    # stop request responsive.
                    t_admit = time.perf_counter()
                    while (not self._stop_requested.is_set()
                           and not self.holding_ticket):
                        self.holding_ticket = admit(timeout=0.05)
                        if self.queue.closed:
                            break
                    if not self.holding_ticket:
                        break
                    with self.tel_lock:
                        self.telemetry.hist(
                            "admit_wait_ms",
                            (time.perf_counter() - t_admit) * 1e3)
                else:
                    # double-buffering throttle (plain TrajectoryQueue
                    # back-compat): once a completed block is already
                    # waiting, collect at most ONE more per published
                    # version — steady-state staleness <= 1 learner step.
                    while (not self._stop_requested.is_set()
                           and self.queue.depth > 0
                           and self.publisher.version <= last_version):
                        time.sleep(0.001)
                if self._stop_requested.is_set():
                    break
                params, version = self.publisher.snapshot(self.worker_id)
                last_version = version
                t0 = time.perf_counter()
                with self.tel_lock:
                    rs, traj = self.collect_fn(params, rs)
                # block for honest iteration wall time (the bounded queue
                # keeps at most `capacity` blocks in flight anyway, so this
                # costs pipelining only at queue depth 0 — learner-bound)
                jax.block_until_ready(traj)
                t1 = time.perf_counter()
                self.latest_rollout_state = rs
                self.iterations += 1
                if self.iterations == 1 and hasattr(self.collect_fn,
                                                    "mark_steady"):
                    with self.tel_lock:
                        self.collect_fn.mark_steady()
                # place onto the learner submesh HERE so the d2d copy
                # overlaps the learner's current update
                block = TrajectoryBlock(
                    traj=put_time_major(traj, self.learner_mesh),
                    rollout_state=put_sharded_state(rs, self.learner_mesh),
                    param_version=version,
                    actor_iter=self.iterations,
                    t_start=t0,
                    t_end=t1,
                    worker_id=self.worker_id,
                )
                placed = False
                while (not placed and not self._stop_requested.is_set()
                       and not self.queue.closed):
                    placed = self.queue.put(block, timeout=0.05)
                if placed:
                    # a successful put consumed the admission ticket
                    # atomically (TrajectoryStore._on_put_locked)
                    self.holding_ticket = False
            if self.holding_ticket and admit is not None:
                # stopped between admit and put: hand the slot back so a
                # graceful stop never strands budget capacity
                self.queue.cancel_ticket()
                self.holding_ticket = False
        except BaseException as e:      # surface to the learner, don't die
            if _chaos.is_silent_death(e):
                # injected pathological mode: die WITHOUT recording the error
                # or closing the queue — the learner's liveness check (not
                # this handler) must notice, restart us, and reclaim any
                # ticket we died holding (holding_ticket stays set)
                self.log(f"[async] actor thread dying silently ({e!r})")
                return
            if self.holding_ticket and admit is not None:
                self.queue.cancel_ticket()
                self.holding_ticket = False
            self.error = e
            self.log(f"[async] actor thread failed: {e!r}")
            self.queue.close()
