"""Podracer-style async actor–learner overlap (sebulba, arXiv:2104.06272).

The fused dispatch (base_runner.make_dispatch_fn) time-slices ONE device set:
the learner idles while envs step and vice versa.  This module overlaps two
programs on disjoint submeshes (parallel/mesh.build_actor_learner_meshes):

- an **actor thread** runs the existing jitted rollout collector continuously
  on the actor submesh, stamping each trajectory block with the param version
  it collected under and pushing it into a bounded queue;
- the **learner** (the main thread, where signal handlers and checkpointing
  live) consumes blocks with the existing streamed PPO update on the learner
  submesh and publishes fresh params device-to-device after every step.

The queue is a host-coordinated ring of DEVICE buffers: blocks are placed
onto the learner submesh at enqueue time (``put_time_major`` /
``put_sharded_state`` device-to-device copies, overlapping the learner's
compute), so the host holds only references and ``capacity`` bounds learner
HBM.  Backpressure blocks the producer — a full queue means the learner is
the bottleneck and more rollouts would only go stale; nothing is ever
dropped (``drops`` is pinned at 0 by tests/test_async_loop.py).

Staleness semantics: the learner accepts 1-step-lagged PPO (bit-exactness
with the synchronous loop is explicitly NOT a goal — convergence parity on
the DCML preset is pinned in BENCHLOG instead).  ``ParamPublisher`` versions
every publish; the lag ``publisher.version - block.param_version`` observed
at consume time feeds the ``staleness_`` gauge family.  A double-buffering
throttle in :class:`ActorWorker` (one new block per published version while
one is already queued) pins steady-state lag at <= 1 even when the actor is
the fast side; the importance-correction hook
(:data:`IMPORTANCE_CORRECTION_DOC`) is the designated seam for off-policy
corrections should transient lag > 1 ever need more than ratio clipping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, NamedTuple, Optional

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.telemetry import Telemetry


class ActorDeadError(RuntimeError):
    """The actor thread is dead (no recorded error, queue still open — the
    silent mode a crashed C extension or injected chaos produces) and the
    restart budget is spent.  Raised by the learner's liveness check instead
    of blocking forever on ``TrajectoryQueue.get``."""


class TrajectoryBlock(NamedTuple):
    """One collected episode chunk in flight from actors to learner."""

    traj: Any                 # Trajectory, placed on the LEARNER submesh
    rollout_state: Any        # post-collect bootstrap state, learner submesh
    param_version: int        # publisher version the actor collected under
    actor_iter: int           # 1-based actor iteration (FIFO assertable)
    t_start: float            # perf_counter at collect launch (actor thread)
    t_end: float              # perf_counter when the block was ready


# The importance-correction hook contract: ``hook(traj, lag) -> traj`` is
# applied by the learner BEFORE the PPO update whenever the consumed block's
# param-version lag is > 0.  The default (None) is the identity — PPO's ratio
# clipping already absorbs the 1-step lag the bounded queue produces in
# steady state (staleness_learner_steps_p95 <= 1, pinned in tests).  A real
# correction (e.g. V-trace-style truncated importance weights over
# ``traj.log_probs``) plugs in here without touching the loop.
ImportanceCorrection = Callable[[Any, int], Any]
IMPORTANCE_CORRECTION_DOC = ImportanceCorrection


class TrajectoryQueue:
    """Bounded FIFO ring of trajectory blocks with blocking backpressure.

    ``put`` blocks while the queue is at capacity (the actor stalls rather
    than dropping or overwriting data — ``drops`` exists only to pin that
    claim in tests); ``get`` blocks while it is empty.  ``close`` wakes every
    waiter; a closed queue rejects puts (``False``) and serves remaining
    blocks until ``drain`` clears them.  Plain host Python — the blocks'
    arrays live on device, the ring only coordinates.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._slots: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.puts = 0
        self.gets = 0
        self.drops = 0          # never incremented: backpressure, not loss
        self.max_depth = 0

    @property
    def depth(self) -> int:
        return len(self._slots)

    @property
    def closed(self) -> bool:
        return self._closed

    def put(self, block, timeout: Optional[float] = None) -> bool:
        """Enqueue, blocking while full.  ``False`` = closed or timed out
        (the block was NOT enqueued; a stopping producer discards it — that
        is shutdown drain, not a drop)."""
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_queue_put()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while len(self._slots) >= self.capacity and not self._closed:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            if self._closed:
                return False
            self._slots.append(block)
            self.puts += 1
            self.max_depth = max(self.max_depth, len(self._slots))
            self._cv.notify_all()
            return True

    def get(self, timeout: Optional[float] = None):
        """Dequeue FIFO, blocking while empty.  ``None`` = closed-and-empty
        or timed out."""
        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_queue_get()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._slots and not self._closed:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)
            if not self._slots:
                return None          # closed and fully drained
            block = self._slots.popleft()
            self.gets += 1
            self._cv.notify_all()
            return block

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self) -> list:
        """Close and return every still-queued block in FIFO order (the
        graceful-stop path: in-flight blocks are coherently discarded and the
        carry resumes from the last CONSUMED episode)."""
        with self._cv:
            self._closed = True
            left = list(self._slots)
            self._slots.clear()
            self._cv.notify_all()
            return left


class ParamPublisher:
    """Versioned device-to-device param broadcast, learner -> actor submesh.

    ``publish`` places the fresh params on the actor submesh through the
    spec layer (``parallel.sharding.place_params`` — one ``device_put`` per
    leaf = direct device-to-device copy, no host staging; ``param_specs``
    default to None = replicated, and learner-side fsdp/tp-sharded inbound
    leaves reshard on the way) and bumps the version; ``snapshot`` hands the
    actor the latest (params, version) pair.  The publish blocks until the
    copy lands so the learner's next (donating) update can never invalidate
    buffers a copy still reads.
    """

    def __init__(self, actor_mesh=None, param_specs=None):
        self._mesh = actor_mesh      # None: single-device / test use
        self._specs = param_specs
        self._lock = threading.Lock()
        self._params = None
        self._version = 0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, params) -> int:
        import jax

        if _chaos.ACTIVE is not None:
            _chaos.ACTIVE.on_param_publish()
        if self._mesh is not None:
            from mat_dcml_tpu.parallel.sharding import place_params

            placed = place_params(params, self._mesh, self._specs)
            jax.block_until_ready(placed)
        else:
            placed = params
        with self._lock:
            self._version += 1
            self._params = placed
            return self._version

    def snapshot(self):
        """Latest ``(params, version)`` — what the next actor iteration
        collects under."""
        with self._lock:
            return self._params, self._version


class ActorWorker(threading.Thread):
    """The actor program: collect continuously, stamp, place, enqueue.

    Owns a PRIVATE :class:`Telemetry` registry (jit instrumentation is not
    thread-safe against the learner's flushes) guarded by ``tel_lock``; the
    learner merges it into the metrics record under the ``async_actor_``
    prefix.  ``latest_rollout_state`` always references the newest completed
    carry — what a graceful stop packs after :meth:`request_stop` joins the
    thread at an iteration boundary.
    """

    def __init__(self, collect_fn, publisher: ParamPublisher,
                 queue: TrajectoryQueue, rollout_state, learner_mesh,
                 telemetry: Optional[Telemetry] = None, log=print):
        super().__init__(name="async-actor", daemon=True)
        self.collect_fn = collect_fn
        self.publisher = publisher
        self.queue = queue
        self.learner_mesh = learner_mesh
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.tel_lock = threading.Lock()
        self.log = log
        self.latest_rollout_state = rollout_state
        self.iterations = 0
        self.error: Optional[BaseException] = None
        # NOT named _stop: threading.Thread has an internal _stop()
        # method that the interpreter calls on thread teardown
        self._stop_requested = threading.Event()

    def request_stop(self) -> None:
        """Ask the actor to exit at its next iteration boundary (the enqueue
        retry loop polls this, so a stop never deadlocks on a full queue)."""
        self._stop_requested.set()

    def run(self) -> None:
        import jax

        from mat_dcml_tpu.parallel.distributed import (
            put_sharded_state,
            put_time_major,
        )

        rs = self.latest_rollout_state
        last_version = -1
        try:
            while not self._stop_requested.is_set():
                if _chaos.ACTIVE is not None:
                    _chaos.ACTIVE.on_actor_iteration(self.iterations + 1)
                # double-buffering throttle: once a completed block is already
                # waiting, collect at most ONE more per published version.  A
                # fast actor otherwise laps the learner and its queued blocks
                # go >1 version stale; with the throttle each block is
                # consumed at its own version or the next one (steady-state
                # staleness <= 1 learner step, pinned in tests), while a slow
                # actor never hits the gate and overlap is unchanged.
                while (not self._stop_requested.is_set()
                       and self.queue.depth > 0
                       and self.publisher.version <= last_version):
                    time.sleep(0.001)
                if self._stop_requested.is_set():
                    break
                params, version = self.publisher.snapshot()
                last_version = version
                t0 = time.perf_counter()
                with self.tel_lock:
                    rs, traj = self.collect_fn(params, rs)
                # block for honest iteration wall time (the bounded queue
                # keeps at most `capacity` blocks in flight anyway, so this
                # costs pipelining only at queue depth 0 — learner-bound)
                jax.block_until_ready(traj)
                t1 = time.perf_counter()
                self.latest_rollout_state = rs
                self.iterations += 1
                if self.iterations == 1 and hasattr(self.collect_fn,
                                                    "mark_steady"):
                    with self.tel_lock:
                        self.collect_fn.mark_steady()
                # place onto the learner submesh HERE so the d2d copy
                # overlaps the learner's current update
                block = TrajectoryBlock(
                    traj=put_time_major(traj, self.learner_mesh),
                    rollout_state=put_sharded_state(rs, self.learner_mesh),
                    param_version=version,
                    actor_iter=self.iterations,
                    t_start=t0,
                    t_end=t1,
                )
                placed = False
                while not placed and not self._stop_requested.is_set():
                    placed = self.queue.put(block, timeout=0.05)
        except BaseException as e:      # surface to the learner, don't die
            if _chaos.is_silent_death(e):
                # injected pathological mode: die WITHOUT recording the error
                # or closing the queue — the learner's liveness check (not
                # this handler) must notice and restart us
                self.log(f"[async] actor thread dying silently ({e!r})")
                return
            self.error = e
            self.log(f"[async] actor thread failed: {e!r}")
            self.queue.close()
