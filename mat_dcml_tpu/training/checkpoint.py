"""Orbax checkpointing: full training state, not just weights.

The reference only saves model weights (``transformer_policy.py:243-248``) —
optimizer and ValueNorm state are lost, so "resume" is weight reload only
(SURVEY.md §5).  Here the whole ``TrainState`` (params, optimizer moments,
ValueNorm statistics, update counter) round-trips, giving true resume.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str | Path, max_to_keep: int = 5):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, train_state) -> None:
        self.manager.save(step, args=ocp.args.StandardSave(train_state))
        self.manager.wait_until_finished()

    def restore(self, step: Optional[int] = None, template=None):
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        if template is not None:
            return self.manager.restore(step, args=ocp.args.StandardRestore(template))
        return self.manager.restore(step)

    @property
    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()
