"""Orbax checkpointing: full training state, not just weights.

The reference only saves model weights (``transformer_policy.py:243-248``) —
optimizer and ValueNorm state are lost, so "resume" is weight reload only
(SURVEY.md §5).  Here the whole ``TrainState`` (params, optimizer moments,
ValueNorm statistics, update counter) round-trips, giving true resume.

Two additions for the serving stack (serving/):

- **async saves**: ``save(..., blocking=False)`` (the default) schedules the
  write and returns — the training loop no longer stalls on checkpoint I/O
  every ``save_interval``.  The previous in-flight save is finalized at the
  *next* save (by which time it has long completed) and in :meth:`close`,
  which the runner's exit path and tests call to guarantee durability.
- **weights-only export**: :func:`export_policy` / :func:`load_policy` write
  just the params subtree plus a JSON manifest (MATConfig fields + obs/act
  space metadata), so a server restores a policy without ever deserializing
  optimizer moments or ValueNorm state — and without importing any trainer.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from mat_dcml_tpu.models.mat import MATConfig

POLICY_MANIFEST = "policy_manifest.json"
_PARAMS_SUBDIR = "params"


class CheckpointManager:
    def __init__(self, directory: str | Path, max_to_keep: int = 5):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, train_state, blocking: bool = False) -> None:
        """Checkpoint ``train_state`` at ``step``.

        ``blocking=False`` (default) returns as soon as the save is scheduled;
        the device->host copy and write happen off-thread (orbax async). The
        previous save is finalized here first, so at most one save is ever in
        flight and the wait is ~free in steady state.  ``blocking=True``
        restores the old synchronous behavior (used right before reads).
        """
        self.manager.wait_until_finished()   # finalize any in-flight save
        self.manager.save(step, args=ocp.args.StandardSave(train_state))
        if blocking:
            self.manager.wait_until_finished()

    def restore(self, step: Optional[int] = None, template=None):
        self.manager.wait_until_finished()   # a just-scheduled save must land
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        if template is not None:
            return self.manager.restore(step, args=ocp.args.StandardRestore(template))
        return self.manager.restore(step)

    def latest_step(self) -> Optional[int]:
        """Most recent finalized checkpoint step (None when empty) — the
        serving loader polls this to pick up fresh exports."""
        return self.manager.latest_step()

    def finish(self) -> None:
        """Finalize any in-flight async save (manager stays usable)."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        """Finalize any in-flight async save and release the manager."""
        self.finish()
        self.manager.close()


# ---------------------------------------------------------------------------
# Weights-only policy export (the serving artifact)
# ---------------------------------------------------------------------------

def export_policy(
    directory: str | Path,
    params,
    mat_config: MATConfig,
    space_meta: Optional[Dict[str, Any]] = None,
    generation: Optional[int] = None,
) -> Path:
    """Write a self-contained serving artifact: params + policy manifest.

    The manifest carries every MATConfig field (round-tripped verbatim by
    :func:`load_policy`) plus free-form ``space_meta`` (env name, obs/act
    space dims/bounds) so a server can validate request shapes without
    importing the env.  No optimizer or ValueNorm state is written.

    ``generation`` is the monotonic ordering counter weight pushers key on
    (``serving/rollout_ctl.WeightPusher`` pushes only strictly newer
    generations).  ``None`` auto-assigns ``1 + max(sibling generations)``
    under the parent directory, so a trainer exporting each interval into
    ``<root>/<step>/`` gets ordered artifacts for free.
    """
    directory = Path(directory).absolute()
    if generation is None:
        generation = next_generation(directory.parent)
    directory.mkdir(parents=True, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(directory / _PARAMS_SUBDIR, params, force=True)
    ckptr.wait_until_finished()
    manifest = {
        "format": "mat_dcml_tpu/policy/v1",
        "generation": int(generation),
        "mat_config": dataclasses.asdict(mat_config),
        "space_meta": space_meta or {},
    }
    (directory / POLICY_MANIFEST).write_text(json.dumps(manifest, indent=2))
    return directory


def read_manifest(directory: str | Path) -> Dict[str, Any]:
    """Parse an export's manifest without touching the params payload."""
    manifest_path = Path(directory).absolute() / POLICY_MANIFEST
    if not manifest_path.exists():
        raise FileNotFoundError(f"no {POLICY_MANIFEST} under {directory}")
    return json.loads(manifest_path.read_text())


def next_generation(root: str | Path) -> int:
    """1 + the highest generation of any export under ``root`` (1 if none).
    Pre-generation manifests count as generation 0."""
    newest = latest_export(root)
    return 1 if newest is None else newest[1] + 1


def latest_export(root: str | Path) -> Optional[Tuple[Path, int]]:
    """Scan ``<root>/*/policy_manifest.json`` and return the export with the
    highest generation as ``(path, generation)``, or None when the root holds
    no exports.  Unreadable manifests are skipped — a half-written export
    (the trainer is mid-save) must not wedge the pusher."""
    root = Path(root).absolute()
    if not root.is_dir():
        return None
    best: Optional[Tuple[Path, int]] = None
    for manifest_path in root.glob(f"*/{POLICY_MANIFEST}"):
        try:
            generation = int(json.loads(manifest_path.read_text())
                             .get("generation", 0))
        except (json.JSONDecodeError, OSError, TypeError, ValueError):
            continue
        if best is None or generation > best[1]:
            best = (manifest_path.parent, generation)
    return best


def load_policy(directory: str | Path) -> Tuple[Any, MATConfig, Dict[str, Any]]:
    """Restore ``(params, MATConfig, space_meta)`` from an export directory.

    The params template comes from re-initializing the model off the
    manifest's MATConfig — structure and dtypes are therefore guaranteed to
    match what the serving forward expects, independent of who exported.
    """
    directory = Path(directory).absolute()
    manifest_path = directory / POLICY_MANIFEST
    if not manifest_path.exists():
        raise FileNotFoundError(f"no {POLICY_MANIFEST} under {directory}")
    manifest = json.loads(manifest_path.read_text())
    if manifest.get("format") != "mat_dcml_tpu/policy/v1":
        raise ValueError(f"unrecognized policy export format: {manifest.get('format')!r}")
    cfg = MATConfig(**manifest["mat_config"])
    # template init on the abstract-eval path only (no real compute/compile)
    from mat_dcml_tpu.models.policy import TransformerPolicy

    template = jax.eval_shape(
        lambda: TransformerPolicy(cfg).init_params(jax.random.key(0))
    )
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(directory / _PARAMS_SUBDIR, target=template)
    return params, cfg, manifest.get("space_meta", {})
