"""Orbax checkpointing: full training state, not just weights.

The reference only saves model weights (``transformer_policy.py:243-248``) —
optimizer and ValueNorm state are lost, so "resume" is weight reload only
(SURVEY.md §5).  Here the whole ``TrainState`` (params, optimizer moments,
ValueNorm statistics, update counter) round-trips, giving true resume.

Two additions for the serving stack (serving/):

- **async saves**: ``save(..., blocking=False)`` (the default) schedules the
  write and returns — the training loop no longer stalls on checkpoint I/O
  every ``save_interval``.  The previous in-flight save is finalized at the
  *next* save (by which time it has long completed) and in :meth:`close`,
  which the runner's exit path and tests call to guarantee durability.
- **weights-only export**: :func:`export_policy` / :func:`load_policy` write
  just the params subtree plus a JSON manifest (MATConfig fields + obs/act
  space metadata), so a server restores a policy without ever deserializing
  optimizer moments or ValueNorm state — and without importing any trainer.

And one for preemption safety (training/resilience.py):

- **integrity manifests + fall-back restore**: every finalized save gets a
  CRC32-per-file manifest under ``<dir>/integrity/<step>.json``, written only
  after orbax finishes the async write.  :meth:`restore_latest_valid` walks
  steps newest→oldest, quarantines any step whose files are missing/
  truncated/bit-flipped (or that orbax can't deserialize) into
  ``<dir>/quarantine/``, and restores the newest step that checks out — a
  relaunch survives a SIGKILL mid-save instead of crashing in restore.  The
  CRC check is the authoritative detector: orbax's ocdbt layout dedups
  content, so a damaged or even missing payload file does NOT reliably make
  ``restore`` raise for small trees.
"""

from __future__ import annotations

import dataclasses
import json
import random
import shutil
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from mat_dcml_tpu.chaos import inject as _chaos
from mat_dcml_tpu.models.mat import MATConfig
from mat_dcml_tpu.training.resilience import backoff_delay

POLICY_MANIFEST = "policy_manifest.json"
_PARAMS_SUBDIR = "params"

INTEGRITY_FORMAT = "mat_dcml_tpu/ckpt-integrity/v1"
_INTEGRITY_SUBDIR = "integrity"
_QUARANTINE_SUBDIR = "quarantine"


def _commit_to_device(tree):
    """Copy restored leaves into device-owned buffers.

    Orbax hands back host numpy arrays, which jit may alias zero-copy on the
    CPU backend — feeding those straight into the donating fused dispatch
    lets XLA write into memory it doesn't own (observed as denormal garbage
    in the resumed train state).  An explicit committed copy makes restored
    state safe to donate."""
    return jax.tree.map(lambda x: jnp.array(x, copy=True), tree)


def _crc32_file(path: Path, chunk: int = 1 << 20) -> int:
    crc = 0
    with path.open("rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


class CheckpointIOError(RuntimeError):
    """Checkpoint IO kept failing after the retry budget — the *persistent*
    failure the crash path is for.  Transient hiccups (NFS blips, preempted
    filers) are retried with jittered backoff and never surface."""


class CheckpointManager:
    def __init__(self, directory: str | Path, max_to_keep: int = 5,
                 telemetry=None, log=print, io_retries: int = 3,
                 io_backoff_base_ms: float = 50.0,
                 sleep: Callable[[float], None] = time.sleep,
                 rand: Callable[[], float] = random.random):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.telemetry = telemetry
        self.log = log
        self.io_retries = int(io_retries)
        self.io_backoff_base_ms = float(io_backoff_base_ms)
        self._sleep = sleep
        self._rand = rand
        self._pending_integrity: list[int] = []
        self.manager = self._make_manager(max_to_keep)
        self._max_to_keep = max_to_keep

    def _io_retry(self, op_name: str, fn: Callable[[], Any]) -> Any:
        """Run one checkpoint IO op under the shared jittered-backoff policy.

        ``OSError`` (the transient class: NFS blips, EIO, injected chaos) is
        retried ``io_retries`` times; exhaustion raises the typed
        :class:`CheckpointIOError` so callers see "storage is actually down",
        not a stack of socket errors.  Anything non-OSError propagates
        untouched — programming errors must not burn the retry budget."""
        attempt = 0
        while True:
            try:
                if _chaos.ACTIVE is not None:
                    _chaos.ACTIVE.on_checkpoint_io(op_name)
                return fn()
            except OSError as e:
                attempt += 1
                if attempt > self.io_retries:
                    if self.telemetry is not None:
                        self.telemetry.count("resilience_checkpoint_io_failures")
                    raise CheckpointIOError(
                        f"checkpoint {op_name} failed {attempt} times "
                        f"(last: {e!r})") from e
                if self.telemetry is not None:
                    self.telemetry.count("resilience_checkpoint_io_retries")
                delay = backoff_delay(attempt, self.io_backoff_base_ms,
                                      rand=self._rand)
                self.log(f"[checkpoint] {op_name} attempt {attempt} failed "
                         f"({e!r}); retrying in {delay * 1e3:.0f}ms")
                self._sleep(delay)

    def _make_manager(self, max_to_keep: int) -> ocp.CheckpointManager:
        return ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, train_state, blocking: bool = False) -> None:
        """Checkpoint ``train_state`` at ``step``.

        ``blocking=False`` (default) returns as soon as the save is scheduled;
        the device->host copy and write happen off-thread (orbax async). The
        previous save is finalized here first, so at most one save is ever in
        flight and the wait is ~free in steady state.  ``blocking=True``
        restores the old synchronous behavior (used right before reads).
        """
        self._finish_and_flush()             # finalize any in-flight save
        self._io_retry("save", lambda: self.manager.save(
            step, args=ocp.args.StandardSave(train_state)))
        self._pending_integrity.append(int(step))
        if blocking:
            self._finish_and_flush()

    def restore(self, step: Optional[int] = None, template=None):
        self._finish_and_flush()             # a just-scheduled save must land
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        # args= always: a bare manager.restore(step) raises KeyError("default")
        # under orbax's registry dispatch when the save went through
        # StandardSave; an empty StandardRestore means "no template"
        restored = self._io_retry("restore", lambda: self.manager.restore(
            step, args=ocp.args.StandardRestore(template)))
        return _commit_to_device(restored)

    def latest_step(self) -> Optional[int]:
        """Most recent finalized checkpoint step (None when empty) — the
        serving loader polls this to pick up fresh exports."""
        return self.manager.latest_step()

    def finish(self) -> None:
        """Finalize any in-flight async save (manager stays usable)."""
        self._finish_and_flush()

    def close(self) -> None:
        """Finalize any in-flight async save and release the manager."""
        self.finish()
        self.manager.close()

    # ------------------------------------------------------------ integrity

    def _finish_and_flush(self) -> None:
        """Wait for in-flight saves, then write integrity manifests for every
        step that just became durable.  The manifest MUST trail the orbax
        finalize — hashing a step that's still being written would bless
        torn bytes."""
        self._io_retry("flush", self.manager.wait_until_finished)
        for step in self._pending_integrity:
            self._write_integrity(step)
            # chaos seam: a finished-and-attested step is what bit-rot
            # injection targets (CRC verification must catch it on restore)
            if _chaos.ACTIVE is not None:
                _chaos.ACTIVE.on_checkpoint_saved(self._step_dir(step))
        self._pending_integrity.clear()

    def _step_dir(self, step: int) -> Path:
        return self.directory / str(int(step))

    def _integrity_path(self, step: int) -> Path:
        return self.directory / _INTEGRITY_SUBDIR / f"{int(step)}.json"

    def _write_integrity(self, step: int) -> None:
        step_dir = self._step_dir(step)
        if not step_dir.is_dir():
            return     # retention already dropped it (max_to_keep)
        files = {}
        for path in sorted(step_dir.rglob("*")):
            if not path.is_file():
                continue
            rel = path.relative_to(step_dir).as_posix()
            files[rel] = {"size": path.stat().st_size, "crc32": _crc32_file(path)}
        manifest = {"format": INTEGRITY_FORMAT, "step": int(step), "files": files}
        ipath = self._integrity_path(step)
        ipath.parent.mkdir(parents=True, exist_ok=True)
        tmp = ipath.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(manifest, indent=1))
        tmp.replace(ipath)

    def verify_step(self, step: int) -> Tuple[str, str]:
        """``("ok" | "unverified" | "bad", reason)`` for one on-disk step.

        "unverified" = no integrity manifest (a pre-manifest legacy save, or
        a crash between finalize and manifest write) — restorable, but not
        CRC-attested.  "bad" = the manifest exists and the step contradicts
        it (missing/truncated/corrupt file)."""
        step_dir = self._step_dir(step)
        if not step_dir.is_dir():
            return "bad", "step directory missing"
        ipath = self._integrity_path(step)
        if not ipath.exists():
            return "unverified", "no integrity manifest"
        try:
            manifest = json.loads(ipath.read_text())
            if manifest.get("format") != INTEGRITY_FORMAT:
                return "unverified", f"unknown manifest format {manifest.get('format')!r}"
            for rel, want in manifest["files"].items():
                path = step_dir / rel
                if not path.is_file():
                    return "bad", f"missing file {rel}"
                if path.stat().st_size != want["size"]:
                    return "bad", f"size mismatch in {rel}"
                if _crc32_file(path) != want["crc32"]:
                    return "bad", f"CRC mismatch in {rel}"
        except (OSError, json.JSONDecodeError, KeyError, TypeError) as e:
            return "unverified", f"unreadable manifest: {e!r}"
        return "ok", "verified"

    def quarantine_step(self, step: int, reason: str) -> None:
        """Move a damaged step (plus its manifest) into ``<dir>/quarantine/``
        and rebuild the orbax manager so its step cache forgets it."""
        qdir = self.directory / _QUARANTINE_SUBDIR / f"{int(step)}.{int(time.time())}"
        qdir.parent.mkdir(parents=True, exist_ok=True)
        step_dir = self._step_dir(step)
        if step_dir.exists():
            shutil.move(str(step_dir), str(qdir))
            (qdir / "quarantine_reason.txt").write_text(reason + "\n")
        ipath = self._integrity_path(step)
        if ipath.exists():
            qdir.mkdir(exist_ok=True)
            shutil.move(str(ipath), str(qdir / ipath.name))
        if self.telemetry is not None:
            self.telemetry.count("resilience_quarantined_steps")
        self.log(f"[checkpoint] quarantined step {step} ({reason}) -> {qdir}")
        self.manager.close()
        self.manager = self._make_manager(self._max_to_keep)

    def restore_latest_valid(self, template=None):
        """``(step, state)`` for the newest step that passes integrity and
        deserializes, quarantining every damaged step it skips on the way
        down; ``(None, None)`` when nothing on disk is usable.

        This is the crash-safe replacement for ``restore()`` in resume paths:
        a SIGKILL mid-save (or bit rot) costs one ``save_interval`` of
        progress instead of wedging the relaunch."""
        self._finish_and_flush()
        steps = sorted(
            (int(p.name) for p in self.directory.iterdir()
             if p.is_dir() and p.name.isdigit()),
            reverse=True,
        )
        for step in steps:
            status, reason = self.verify_step(step)
            if status == "bad":
                self.quarantine_step(step, reason)
                continue
            if status == "unverified":
                self.log(f"[checkpoint] step {step} has no integrity manifest "
                         f"({reason}); restoring unverified")
            try:
                # args= always — see restore(); transient IO retries first,
                # so only persistent/corrupt steps reach quarantine
                state = self._io_retry(
                    "restore", lambda: self.manager.restore(
                        step, args=ocp.args.StandardRestore(template)))
            except Exception as e:
                self.quarantine_step(step, f"unreadable: {e!r}")
                continue
            return step, _commit_to_device(state)
        return None, None


# ---------------------------------------------------------------------------
# Weights-only policy export (the serving artifact)
# ---------------------------------------------------------------------------

def export_policy(
    directory: str | Path,
    params,
    mat_config: MATConfig,
    space_meta: Optional[Dict[str, Any]] = None,
    generation: Optional[int] = None,
) -> Path:
    """Write a self-contained serving artifact: params + policy manifest.

    The manifest carries every MATConfig field (round-tripped verbatim by
    :func:`load_policy`) plus free-form ``space_meta`` (env name, obs/act
    space dims/bounds) so a server can validate request shapes without
    importing the env.  No optimizer or ValueNorm state is written.

    ``generation`` is the monotonic ordering counter weight pushers key on
    (``serving/rollout_ctl.WeightPusher`` pushes only strictly newer
    generations).  ``None`` auto-assigns ``1 + max(sibling generations)``
    under the parent directory, so a trainer exporting each interval into
    ``<root>/<step>/`` gets ordered artifacts for free.
    """
    directory = Path(directory).absolute()
    if generation is None:
        generation = next_generation(directory.parent)
    directory.mkdir(parents=True, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(directory / _PARAMS_SUBDIR, params, force=True)
    ckptr.wait_until_finished()
    manifest = {
        "format": "mat_dcml_tpu/policy/v1",
        "generation": int(generation),
        "mat_config": dataclasses.asdict(mat_config),
        "space_meta": space_meta or {},
    }
    (directory / POLICY_MANIFEST).write_text(json.dumps(manifest, indent=2))
    return directory


def read_manifest(directory: str | Path) -> Dict[str, Any]:
    """Parse an export's manifest without touching the params payload."""
    manifest_path = Path(directory).absolute() / POLICY_MANIFEST
    if not manifest_path.exists():
        raise FileNotFoundError(f"no {POLICY_MANIFEST} under {directory}")
    return json.loads(manifest_path.read_text())


def next_generation(root: str | Path) -> int:
    """1 + the highest generation of any export under ``root`` (1 if none).
    Pre-generation manifests count as generation 0."""
    newest = latest_export(root)
    return 1 if newest is None else newest[1] + 1


def latest_export(root: str | Path) -> Optional[Tuple[Path, int]]:
    """Scan ``<root>/*/policy_manifest.json`` and return the export with the
    highest generation as ``(path, generation)``, or None when the root holds
    no exports.  Unreadable manifests are skipped — a half-written export
    (the trainer is mid-save) must not wedge the pusher."""
    root = Path(root).absolute()
    if not root.is_dir():
        return None
    best: Optional[Tuple[Path, int]] = None
    for manifest_path in root.glob(f"*/{POLICY_MANIFEST}"):
        try:
            generation = int(json.loads(manifest_path.read_text())
                             .get("generation", 0))
        except (json.JSONDecodeError, OSError, TypeError, ValueError):
            continue
        if best is None or generation > best[1]:
            best = (manifest_path.parent, generation)
    return best


def load_policy(directory: str | Path) -> Tuple[Any, MATConfig, Dict[str, Any]]:
    """Restore ``(params, MATConfig, space_meta)`` from an export directory.

    The params template comes from re-initializing the model off the
    manifest's MATConfig — structure and dtypes are therefore guaranteed to
    match what the serving forward expects, independent of who exported.
    """
    directory = Path(directory).absolute()
    manifest_path = directory / POLICY_MANIFEST
    if not manifest_path.exists():
        raise FileNotFoundError(f"no {POLICY_MANIFEST} under {directory}")
    manifest = json.loads(manifest_path.read_text())
    if manifest.get("format") != "mat_dcml_tpu/policy/v1":
        raise ValueError(f"unrecognized policy export format: {manifest.get('format')!r}")
    cfg = MATConfig(**manifest["mat_config"])
    # template init on the abstract-eval path only (no real compute/compile)
    from mat_dcml_tpu.models.policy import TransformerPolicy

    template = jax.eval_shape(
        lambda: TransformerPolicy(cfg).init_params(jax.random.key(0))
    )
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(directory / _PARAMS_SUBDIR, target=template)
    return params, cfg, manifest.get("space_meta", {})
