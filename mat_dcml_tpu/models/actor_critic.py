"""MLP/GRU actor-critic models for the PPO/MAPPO/HAPPO/HATRPO/IPPO families.

JAX equivalents of ``mat/algorithms/actor_critic.py`` (shared by HAPPO/PPO/
IPPO) and ``r_mappo/algorithm/r_actor_critic.py`` (recurrent MAPPO):

- ``Actor``: base (MLP or CNN) -> optional mask-gated GRU -> ACT head
  (``actor_critic.py:11-116``).
- ``Critic``: base over centralized obs -> optional GRU -> scalar value head;
  with PopArt the head's outputs live in normalized-return space and the
  trainer rescales its weights when statistics update
  (``actor_critic.py:119-171``, ``algorithms/utils/popart.py``).

All methods are row-major ``(N, d)`` like the reference's flattened
(threads x agents) batches; recurrent hidden states are ``(N, recurrent_N,
hidden)``.  Per-agent (non-shared) families stack parameter pytrees along a
leading agent axis and ``vmap`` these same modules.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.spaces import Box, DCMLActionSpace, Discrete
from mat_dcml_tpu.models.act_layer import ACTLayer
from mat_dcml_tpu.models.bases import CNNBase, GRULayer, MLPBase
from mat_dcml_tpu.ops import distributions as D


@dataclasses.dataclass(frozen=True)
class ACConfig:
    """Network hyperparameters (``config.py`` network group defaults)."""

    hidden_size: int = 64
    layer_N: int = 1
    use_relu: bool = True
    use_feature_normalization: bool = True
    use_recurrent_policy: bool = False
    recurrent_N: int = 1
    std_x_coef: float = 1.0
    std_y_coef: float = 0.5
    image_obs: bool = False


def _mixed_out_dim(space) -> Optional[int]:
    if isinstance(space, DCMLActionSpace) and space.mixed:
        return space.mixed_feature_dim
    return None


class Actor(nn.Module):
    cfg: ACConfig
    space: object

    def setup(self):
        c = self.cfg
        out_dim = _mixed_out_dim(self.space)
        if c.image_obs:
            self.base = CNNBase(c.hidden_size, c.use_relu)
        else:
            self.base = MLPBase(
                c.hidden_size, c.layer_N, c.use_relu, c.use_feature_normalization, out_dim
            )
        if c.use_recurrent_policy:
            if out_dim is not None:
                raise ValueError("recurrent policy is incompatible with the mixed "
                                 "action space's wide feature head")
            self.rnn = GRULayer(c.hidden_size, c.recurrent_N)
        self.act = ACTLayer(self.space, c.std_x_coef, c.std_y_coef)

    def _features(self, obs, rnn_states, masks):
        x = self.base(obs)
        if self.cfg.use_recurrent_policy:
            x, rnn_states = self.rnn(x, rnn_states, masks)
        return x, rnn_states

    def __call__(self, obs, rnn_states, masks, available_actions=None,
                 deterministic: bool = False, key: Optional[jax.Array] = None):
        """Rollout step (``actor_critic.py:42-73``) -> (action, logp, h')."""
        x, rnn_states = self._features(obs, rnn_states, masks)
        if key is None:
            if not deterministic:
                raise ValueError("stochastic sampling requires an explicit PRNG key")
            key = jax.random.key(0)  # never consumed on the deterministic path
        action, logp = self.act.sample(x, key, available_actions, deterministic)
        return action, logp, rnn_states

    def evaluate(self, obs, rnn_states, action, masks, available_actions=None,
                 active_masks=None):
        """Training-time scoring (``actor_critic.py:75-117``) -> (logp, ent)."""
        x, _ = self._features(obs, rnn_states, masks)
        return self.act.evaluate(x, action, available_actions, active_masks)

    def evaluate_seq(self, obs, rnn_states, action, masks, available_actions=None,
                     active_masks=None):
        """Recurrent training over ``(T, B, ...)`` sequences: the reference's
        chunked recurrent generator path (``separated_buffer.py:236-430``)."""
        if not self.cfg.use_recurrent_policy:
            raise ValueError("evaluate_seq requires use_recurrent_policy=True")
        x = self.base(obs)
        x, _ = self.rnn.run_sequence(x, rnn_states, masks)
        return self.act.evaluate(x, action, available_actions, active_masks)

    def dist_params(self, obs, rnn_states, masks, available_actions=None):
        """HATRPO KL machinery: distribution parameters
        (``act.py:evaluate_actions_trpo``).  Discrete -> masked logits;
        Box/extra -> (mean, std)."""
        x, _ = self._features(obs, rnn_states, masks)
        return self._dist_from_features(x, available_actions)

    def dist_params_seq(self, obs, rnn_states, masks, available_actions=None):
        """``dist_params`` over ``(T, B, ...)`` sequences from a chunk-start
        hidden state — the recurrent HATRPO KL path."""
        if not self.cfg.use_recurrent_policy:
            raise ValueError("dist_params_seq requires use_recurrent_policy=True")
        x = self.base(obs)
        x, _ = self.rnn.run_sequence(x, rnn_states, masks)
        return self._dist_from_features(x, available_actions)

    def _dist_from_features(self, x, available_actions):
        sp = self.space
        if isinstance(sp, Discrete) or (
            isinstance(sp, DCMLActionSpace) and not sp.mixed and not sp.extra
        ):
            return D.mask_logits(self.act.action_head(x), available_actions)
        if isinstance(sp, Box) or (isinstance(sp, DCMLActionSpace) and sp.extra):
            mean = self.act.mean_head(x)
            std = jnp.broadcast_to(self.act._gauss_std(self.act.log_std), mean.shape)
            return mean, std
        raise TypeError(f"dist_params unsupported for {sp!r}")


class Critic(nn.Module):
    cfg: ACConfig
    n_objective: int = 1

    def setup(self):
        c = self.cfg
        if c.image_obs:
            self.base = CNNBase(c.hidden_size, c.use_relu)
        else:
            self.base = MLPBase(c.hidden_size, c.layer_N, c.use_relu, c.use_feature_normalization)
        if c.use_recurrent_policy:
            self.rnn = GRULayer(c.hidden_size, c.recurrent_N)
        # PopArt and plain heads share this layout; PopArt weight rescaling is
        # a functional transform applied by the trainer (ops/popart.py).
        self.v_out = nn.Dense(
            self.n_objective,
            kernel_init=nn.initializers.orthogonal(1.0),
            bias_init=nn.initializers.zeros_init(),
        )

    def __call__(self, cent_obs, rnn_states, masks):
        x = self.base(cent_obs)
        if self.cfg.use_recurrent_policy:
            x, rnn_states = self.rnn(x, rnn_states, masks)
        return self.v_out(x), rnn_states

    def values_seq(self, cent_obs, rnn_states, masks):
        if not self.cfg.use_recurrent_policy:
            raise ValueError("values_seq requires use_recurrent_policy=True")
        x = self.base(cent_obs)
        x, _ = self.rnn.run_sequence(x, rnn_states, masks)
        return self.v_out(x)


class ACOutput(NamedTuple):
    value: jax.Array
    action: jax.Array
    log_prob: jax.Array
    actor_h: jax.Array
    critic_h: jax.Array


class ActorCriticPolicy:
    """Functional bundle over {actor, critic} params — the JAX counterpart of
    ``rMAPPOPolicy.py`` / ``happo_policy.py`` / ``ippo_policy.py``."""

    def __init__(self, cfg: ACConfig, obs_dim: int, cent_obs_dim: int, space,
                 n_objective: int = 1):
        self.cfg = cfg
        self.space = space
        self.obs_dim = obs_dim
        self.cent_obs_dim = cent_obs_dim
        self.actor = Actor(cfg, space)
        self.critic = Critic(cfg, n_objective)

    def init_hidden(self, n: int) -> Tuple[jax.Array, jax.Array]:
        h = jnp.zeros((n, self.cfg.recurrent_N, self.cfg.hidden_size), jnp.float32)
        return h, h

    def init_params(self, key: jax.Array):
        k_a, k_c = jax.random.split(key)
        obs = jnp.zeros((1, self.obs_dim), jnp.float32)
        cent = jnp.zeros((1, self.cent_obs_dim), jnp.float32)
        h, _ = self.init_hidden(1)
        mask = jnp.ones((1, 1), jnp.float32)
        return {
            "actor": self.actor.init(k_a, obs, h, mask, None, False, jax.random.key(0)),
            "critic": self.critic.init(k_c, cent, h, mask),
        }

    def get_actions(self, params, key, cent_obs, obs, actor_h, critic_h, masks,
                    available_actions=None, deterministic: bool = False) -> ACOutput:
        action, logp, actor_h = self.actor.apply(
            params["actor"], obs, actor_h, masks, available_actions, deterministic, key
        )
        value, critic_h = self.critic.apply(params["critic"], cent_obs, critic_h, masks)
        return ACOutput(value, action, logp, actor_h, critic_h)

    def get_values(self, params, cent_obs, critic_h, masks):
        value, _ = self.critic.apply(params["critic"], cent_obs, critic_h, masks)
        return value

    def evaluate_actions(self, params, cent_obs, obs, actor_h, critic_h, action,
                         masks, available_actions=None, active_masks=None):
        logp, ent = self.actor.apply(
            params["actor"], obs, actor_h, action, masks, available_actions,
            active_masks, method="evaluate",
        )
        value, _ = self.critic.apply(params["critic"], cent_obs, critic_h, masks)
        return value, logp, ent

    def evaluate_actions_seq(self, params, cent_obs, obs, actor_h0, critic_h0,
                             action, masks, available_actions=None, active_masks=None):
        """Sequence (T, B, ...) evaluation for recurrent training."""
        logp, ent = self.actor.apply(
            params["actor"], obs, actor_h0, action, masks, available_actions,
            active_masks, method="evaluate_seq",
        )
        value = self.critic.apply(
            params["critic"], cent_obs, critic_h0, masks, method="values_seq"
        )
        return value, logp, ent

    def act(self, params, key, obs, actor_h, masks, available_actions=None,
            deterministic: bool = False):
        action, logp, actor_h = self.actor.apply(
            params["actor"], obs, actor_h, masks, available_actions, deterministic, key
        )
        return action, logp, actor_h
