"""Model zoo: MAT encoder-decoder and its ablations, MLP/RNN actor-critics."""

from mat_dcml_tpu.models.mat import MATConfig, MultiAgentTransformer
from mat_dcml_tpu.models.policy import TransformerPolicy
