"""The Multi-Agent Transformer as a Flax module.

Reference: ``mat_src/mat/algorithms/mat/algorithm/ma_transformer.py``.  The
encoder doubles as the critic — its head emits per-agent values off the same
trunk that produces ``obs_rep`` (``ma_transformer.py:141-154``); the decoder
autoregressively maps previous agents' actions + ``obs_rep`` to the current
agent's logits (``ma_transformer.py:157-230``).

Action-type semantics (``ma_transformer.py:283-295``):
  - ``discrete``: one categorical head per agent.
  - ``semi_discrete``: the DCML mode — agents ``[0, n_agent+semi_index)`` are
    categorical (worker-selection bits), the tail agents are Gaussian with
    ``std = sigmoid(log_std) * 0.5`` (the coding-ratio agent)
    (``transformer_act.py:30-129``).
  - ``continuous``: Gaussian over all dims.
  - ``available_continuous``: per-agent one-hot discrete part + Gaussian tail
    concatenated (``transformer_act.py:234-322``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.modules import (
    gelu,
    DecodeBlock,
    EncodeBlock,
    GAIN_ACT,
    dense,
    init_decode_cache,
    init_packed_cache,
)
from mat_dcml_tpu.telemetry.scopes import named_scope, probe

DISCRETE = "discrete"
SEMI_DISCRETE = "semi_discrete"
CONTINUOUS = "continuous"
AVAILABLE_CONTINUOUS = "available_continuous"

NORMAL_STD = 0.5  # transformer_act.py:6


@dataclasses.dataclass(frozen=True)
class MATConfig:
    n_agent: int
    obs_dim: int
    state_dim: int
    action_dim: int
    n_block: int = 2
    n_embd: int = 64
    n_head: int = 2
    action_type: str = DISCRETE
    semi_index: int = -1          # number of trailing continuous agents, negated
    discrete_dim: int = 2         # available_continuous: leading one-hot dims
    encode_state: bool = False
    dec_actor: bool = False       # "MAT-Dec" ablation (ma_transformer.py:175-189)
    share_actor: bool = False
    n_objective: int = 1          # >1 => MO-MAT vector-valued critic
    # computation dtype for the transformer trunk ("float32" | "bfloat16");
    # params, action/value heads, softmax, and distributions stay float32 —
    # bfloat16 keeps the trunk matmuls on the TPU MXU fast path
    dtype: str = "float32"
    # rematerialize transformer blocks in the backward pass (jax.checkpoint):
    # activations per block drop from O(B*A*A + B*A*D) to block boundaries,
    # trading ~1/3 extra forward FLOPs for the big-batch PPO update fitting
    # in HBM.  Decode (forward-only) is unaffected.
    remat: bool = False

    @property
    def np_dtype(self):
        import jax.numpy as _jnp

        return {"float32": _jnp.float32, "bfloat16": _jnp.bfloat16}[self.dtype]

    @property
    def action_input_dim(self) -> int:
        # Discrete-style decoders consume one-hot + start-token slot.
        if self.action_type in (DISCRETE, SEMI_DISCRETE, AVAILABLE_CONTINUOUS):
            return self.action_dim + 1
        return self.action_dim

    @property
    def n_discrete_agents(self) -> int:
        """Agents with categorical heads in semi-discrete mode."""
        return self.n_agent + self.semi_index


class ObsEncoder(nn.Module):
    """LayerNorm -> Linear -> GELU embed (``ma_transformer.py:131-134``)."""

    n_embd: int
    dtype: object = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = dense(self.n_embd, gain=GAIN_ACT, dtype=self.dtype)(x)
        return gelu(x)


class Head(nn.Module):
    """Linear-GELU-LN-Linear head (``ma_transformer.py:138-139,202-203``).

    Always float32: logits and values feed distributions/losses, where
    bfloat16 rounding would perturb PPO ratios."""

    n_embd: int
    out_dim: int

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.float32)
        x = dense(self.n_embd, gain=GAIN_ACT)(x)
        x = gelu(x)
        x = nn.LayerNorm()(x)
        return dense(self.out_dim)(x)


class Encoder(nn.Module):
    """Value head + shared representation (``ma_transformer.py:119-154``)."""

    cfg: MATConfig

    def setup(self):
        c = self.cfg
        dt = c.np_dtype if c.dtype != "float32" else None
        self.state_encoder = ObsEncoder(c.n_embd, dtype=dt)
        self.obs_encoder = ObsEncoder(c.n_embd, dtype=dt)
        self.ln = nn.LayerNorm(dtype=dt)
        blk_cls = nn.remat(EncodeBlock) if c.remat else EncodeBlock
        self.blocks = [blk_cls(c.n_embd, c.n_head, dtype=dt) for _ in range(c.n_block)]
        self.head = Head(c.n_embd, c.n_objective)

    def __call__(self, state: jax.Array, obs: jax.Array):
        with named_scope("mat/encoder"):
            x = self.state_encoder(state) if self.cfg.encode_state else self.obs_encoder(obs)
            rep = self.ln(x)
            for blk in self.blocks:
                rep = blk(rep)
            v_loc = self.head(rep)
            probe("mat/encoder", {"rep": rep, "v_loc": v_loc})
            return v_loc, rep


class DecActorMlp(nn.Module):
    """Per-agent (or shared) MLP actor for the MAT-Dec ablation
    (``ma_transformer.py:175-189``): LN-Linear-GELU-LN-Linear-GELU-LN-Linear."""

    n_embd: int
    action_dim: int

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        x = nn.LayerNorm()(obs)
        x = gelu(dense(self.n_embd, gain=GAIN_ACT)(x))
        x = nn.LayerNorm()(x)
        x = gelu(dense(self.n_embd, gain=GAIN_ACT)(x))
        x = nn.LayerNorm()(x)
        return dense(self.action_dim)(x)


class Decoder(nn.Module):
    """Action-conditioned decoder (``ma_transformer.py:157-230``)."""

    cfg: MATConfig

    def setup(self):
        c = self.cfg
        if c.action_type != DISCRETE:
            # std parameterized as sigmoid(log_std) * 0.5, init log_std = 1
            # (ma_transformer.py:169-172, transformer_act.py:59).
            self.log_std = self.param("log_std", lambda k: jnp.ones((c.action_dim,)))
        if c.dec_actor:
            if c.share_actor:
                self.mlp = DecActorMlp(c.n_embd, c.action_dim)
            else:
                # One MLP per agent, vmapped over stacked parameters.
                self.mlp = nn.vmap(
                    DecActorMlp,
                    in_axes=1,
                    out_axes=1,
                    axis_size=c.n_agent,
                    variable_axes={"params": 0},
                    split_rngs={"params": True},
                )(c.n_embd, c.action_dim)
        else:
            dt = c.np_dtype if c.dtype != "float32" else None
            if c.action_type in (DISCRETE, SEMI_DISCRETE):
                self.action_encoder_nobias = dense(c.n_embd, gain=GAIN_ACT, use_bias=False, dtype=dt)
            else:
                self.action_encoder_bias = dense(c.n_embd, gain=GAIN_ACT, dtype=dt)
            self.obs_encoder = ObsEncoder(c.n_embd, dtype=dt)
            self.ln = nn.LayerNorm(dtype=dt)
            # remat wraps __call__ only: the teacher-forced training pass is
            # rematerialized, the (forward-only) decode_step path is untouched
            blk_cls = nn.remat(DecodeBlock) if c.remat else DecodeBlock
            self.blocks = [blk_cls(c.n_embd, c.n_head, dtype=dt) for _ in range(c.n_block)]
            self.head = Head(c.n_embd, c.action_dim)

    def _embed_action(self, shifted_action: jax.Array) -> jax.Array:
        if self.cfg.action_type in (DISCRETE, SEMI_DISCRETE):
            return gelu(self.action_encoder_nobias(shifted_action))
        return gelu(self.action_encoder_bias(shifted_action))

    def __call__(self, shifted_action: jax.Array, obs_rep: jax.Array, obs: jax.Array) -> jax.Array:
        """Full teacher-forced pass -> ``(B, n_agent, action_dim)`` logits."""
        with named_scope("mat/decoder"):
            if self.cfg.dec_actor:
                logits = self.mlp(obs)
            else:
                x = self.ln(self._embed_action(shifted_action))
                for blk in self.blocks:
                    x = blk(x, obs_rep)
                logits = self.head(x)
            probe("mat/decoder", {"logits": logits})
            return logits

    def decode_step(self, shifted_action_i: jax.Array, rep_i: jax.Array, obs_i: jax.Array, caches, i):
        """One autoregressive position with KV caches.

        Args:
          shifted_action_i: ``(B, 1, action_input_dim)`` previous agent's
            (one-hot) action, or the start token at i = 0.
          rep_i: ``(B, 1, n_embd)`` encoder rep at position i.
          obs_i: ``(B, 1, obs_dim)`` obs at position i (dec_actor mode only).
          caches: list of per-block KV cache dicts.
          i: scalar agent index.

        Returns:
          ``(B, 1, action_dim)`` logits and updated caches.
        """
        with named_scope("mat/decoder_step"):
            if self.cfg.dec_actor:
                return self.mlp(obs_i) if self.cfg.share_actor else self._dec_actor_step(obs_i, i), caches
            x = self.ln(self._embed_action(shifted_action_i))
            new_caches = []
            for blk, cache in zip(self.blocks, caches):
                x, cache = blk.decode_step(x, rep_i, cache, i)
                new_caches.append(cache)
            return self.head(x), new_caches

    def decode_queries(self, obs_rep: jax.Array) -> jax.Array:
        """Hoisted cross-attn query projections for the cached decode.

        ``obs_rep`` is fully known before the decode loop starts, so every
        block's attn2 query projection — ``query_p(rep_i)`` inside
        ``decode_step`` — can be computed for all A positions in one batched
        matmul per block.  Returns ``(n_block, B, H, A, Dh)``; slicing
        position ``i`` reproduces the per-step projection bit-for-bit
        (tests/test_cached_decode.py).  Not supported for ``dec_actor``.
        """
        if self.cfg.dec_actor:
            raise ValueError("decode_queries does not support dec_actor")
        return jnp.stack(
            [blk.attn2.project_q_heads(obs_rep) for blk in self.blocks]
        )

    def decode_step_cached(self, shifted_action_i: jax.Array, rep_i: jax.Array,
                           q2_i: jax.Array, kv, i):
        """One autoregressive position against the packed head-split cache.

        The O(1)-per-step twin of :meth:`decode_step`: K/V live pre-split in
        two stacked ``(2 * n_block, B, H, A, Dh)`` buffers and the cross-attn
        queries arrive pre-projected, so each step's new work is one column
        write and one masked attention per plane.  Bit-exact to
        :meth:`decode_step` (tests/test_cached_decode.py).

        Args:
          shifted_action_i: ``(B, 1, action_input_dim)`` previous agent's
            (one-hot) action, or the start token at i = 0.
          rep_i: ``(B, 1, n_embd)`` encoder rep at position i.
          q2_i: ``(n_block, B, H, 1, Dh)`` pre-projected cross-attn queries
            at position i (a slice of :meth:`decode_queries`).
          kv: ``(k_buf, v_buf)`` packed cache pair.
          i: scalar agent index.

        Returns:
          ``(B, 1, action_dim)`` logits and the updated ``(k_buf, v_buf)``.
        """
        with named_scope("mat/decoder_step_cached"):
            if self.cfg.dec_actor:
                raise ValueError("decode_step_cached does not support dec_actor")
            x = self.ln(self._embed_action(shifted_action_i))
            A = kv[0].shape[3]
            valid = jnp.arange(A) <= i
            for bi, blk in enumerate(self.blocks):
                x, kv = blk.decode_step_packed(
                    x, rep_i, q2_i[bi], kv, 2 * bi, i, valid
                )
            return self.head(x), kv

    def decode_block(self, shifted_action_w: jax.Array, rep_w: jax.Array, caches, start):
        """A window of ``K`` consecutive positions with KV caches (the
        speculative draft-verify pass).  Not supported for ``dec_actor`` —
        that ablation has no cached decode to speculate over.

        Args:
          shifted_action_w: ``(B, K, action_input_dim)`` window inputs
            (previous agents' one-hot actions / start token at position 0).
          rep_w: ``(B, K, n_embd)`` encoder rep over the window.
          caches: list of per-block KV cache dicts.
          start: scalar window start index (``start + K <= n_agent``).

        Returns:
          ``(B, K, action_dim)`` logits and updated caches.
        """
        with named_scope("mat/decoder_block"):
            if self.cfg.dec_actor:
                raise ValueError("decode_block does not support dec_actor")
            x = self.ln(self._embed_action(shifted_action_w))
            new_caches = []
            for blk, cache in zip(self.blocks, caches):
                x, cache = blk.decode_block(x, rep_w, cache, start)
                new_caches.append(cache)
            return self.head(x), new_caches

    def _dec_actor_step(self, obs_i: jax.Array, i):
        # Per-agent MLP selected by index: run all agents' MLPs on the same
        # obs and gather row i (tiny model; avoids dynamic param indexing).
        logits = self.mlp(jnp.broadcast_to(obs_i, (obs_i.shape[0], self.cfg.n_agent, obs_i.shape[-1])))
        return jax.lax.dynamic_slice_in_dim(logits, i, 1, axis=1)

    def std(self) -> jax.Array:
        return jax.nn.sigmoid(self.log_std) * NORMAL_STD


class MultiAgentTransformer(nn.Module):
    """Wrapper exposing encode / decode methods for functional use
    (``ma_transformer.py:233-339``)."""

    cfg: MATConfig

    def setup(self):
        self.encoder = Encoder(self.cfg)
        self.decoder = Decoder(self.cfg)

    def __call__(self, state: jax.Array, obs: jax.Array, shifted_action: jax.Array):
        """Init-path: touches both encoder and decoder parameters."""
        v_loc, rep = self.encoder(state, obs)
        logits = self.decoder(shifted_action, rep, obs)
        return v_loc, rep, logits

    def encode(self, state: jax.Array, obs: jax.Array):
        return self.encoder(state, obs)

    def decode_full(self, shifted_action: jax.Array, obs_rep: jax.Array, obs: jax.Array):
        return self.decoder(shifted_action, obs_rep, obs)

    def decode_step(self, shifted_action_i, rep_i, obs_i, caches, i):
        return self.decoder.decode_step(shifted_action_i, rep_i, obs_i, caches, i)

    def decode_block(self, shifted_action_w, rep_w, caches, start):
        return self.decoder.decode_block(shifted_action_w, rep_w, caches, start)

    def decode_queries(self, obs_rep):
        return self.decoder.decode_queries(obs_rep)

    def decode_step_cached(self, shifted_action_i, rep_i, q2_i, kv, i):
        return self.decoder.decode_step_cached(shifted_action_i, rep_i, q2_i, kv, i)

    def action_std(self):
        return self.decoder.std()

    def fresh_cache(self, batch: int, dtype=None):
        dtype = dtype if dtype is not None else self.cfg.np_dtype
        return init_decode_cache(self.cfg.n_block, batch, self.cfg.n_agent, self.cfg.n_embd, dtype)

    def fresh_packed_cache(self, batch: int, dtype=None):
        """Packed head-split K/V pair for :meth:`decode_step_cached`."""
        dtype = dtype if dtype is not None else self.cfg.np_dtype
        return init_packed_cache(
            self.cfg.n_block, batch, self.cfg.n_agent, self.cfg.n_embd,
            self.cfg.n_head, dtype,
        )
