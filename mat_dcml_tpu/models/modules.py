"""Shared Flax building blocks for the MAT family.

Initialization mirrors the reference (``ma_transformer.py:18-21``): orthogonal
kernels with gain 0.01 (or the ReLU gain ~sqrt(2) for "activated" layers) and
zero biases.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from mat_dcml_tpu.ops.attention import merge_heads, multi_head_attention, split_heads

GAIN_ACT = math.sqrt(2.0)  # torch nn.init.calculate_gain('relu')
GAIN_OUT = 0.01


def gelu(x):
    """Exact (erf) GELU — torch's nn.GELU default; flax's nn.gelu defaults to
    the tanh approximation, which diverges from the reference by ~1e-3."""
    return jax.nn.gelu(x, approximate=False)


def dense(features: int, gain: float = GAIN_OUT, use_bias: bool = True,
          dtype=None) -> nn.Dense:
    """``dtype``: computation dtype (params stay float32 — flax param_dtype
    default); bfloat16 here keeps the matmuls on the MXU fast path."""
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=dtype,
        kernel_init=nn.initializers.orthogonal(gain),
        bias_init=nn.initializers.zeros,
    )


class SelfAttention(nn.Module):
    """QKV attention over the agent axis (``ma_transformer.py:24-69``).

    Exposes split projection helpers so the KV-cached decode path can reuse
    exactly the same parameters as the full forward.
    """

    n_embd: int
    n_head: int
    masked: bool = False
    dtype: Optional[jnp.dtype] = None

    def setup(self):
        assert self.n_embd % self.n_head == 0
        self.key_p = dense(self.n_embd, dtype=self.dtype)
        self.query_p = dense(self.n_embd, dtype=self.dtype)
        self.value_p = dense(self.n_embd, dtype=self.dtype)
        self.proj = dense(self.n_embd, dtype=self.dtype)

    def __call__(self, key: jax.Array, value: jax.Array, query: jax.Array) -> jax.Array:
        k = split_heads(self.key_p(key), self.n_head)
        q = split_heads(self.query_p(query), self.n_head)
        v = split_heads(self.value_p(value), self.n_head)
        y = multi_head_attention(q, k, v, causal=self.masked)
        return self.proj(merge_heads(y))

    def project_kv(self, x: jax.Array):
        """Raw (pre-head-split) key/value projections for cache writes."""
        return self.key_p(x), self.value_p(x)

    def project_q_heads(self, x: jax.Array) -> jax.Array:
        """Head-split query projection ``(B, L, D) -> (B, H, L, Dh)``.

        Position-independent, so the cached decode hoists it out of the scan:
        one ``(B, A, D)`` matmul replaces A per-step ``(B, 1, D)`` matmuls.
        Slicing a row of the batched result is bitwise-equal to projecting
        that row alone (pinned in tests/test_cached_decode.py)."""
        return split_heads(self.query_p(x), self.n_head)

    def project_kv_heads(self, x: jax.Array):
        """Head-split key/value projections for packed-cache writes.

        ``split_heads`` is pure data movement, so storing the cache head-split
        holds exactly the values :meth:`attend_cached` reconstructs per step —
        minus the per-step whole-cache transpose."""
        return (
            split_heads(self.key_p(x), self.n_head),
            split_heads(self.value_p(x), self.n_head),
        )

    def attend_heads(self, q_heads: jax.Array, k_heads: jax.Array,
                     v_heads: jax.Array, kv_mask: jax.Array) -> jax.Array:
        """Attention over an already-head-split query and cache.

        Same einsum/softmax program as :meth:`attend_cached` — the operands
        are value-identical (head-splitting commutes with the cache write),
        so the cached decode path stays bit-exact to the scan path.

        Args:
          q_heads: ``(B, H, Lq, Dh)`` head-split projected queries.
          k_heads / v_heads: ``(B, H, L, Dh)`` head-split cache planes.
          kv_mask: ``(L,)`` validity mask.
        """
        y = multi_head_attention(q_heads, k_heads, v_heads, kv_mask=kv_mask)
        return self.proj(merge_heads(y))

    def attend_cached(self, query: jax.Array, k_cache: jax.Array, v_cache: jax.Array, kv_mask: jax.Array) -> jax.Array:
        """Attention for a single query position over a static-length cache.

        Args:
          query: ``(B, 1, D)`` un-projected query input.
          k_cache / v_cache: ``(B, L, D)`` raw projections; positions where
            ``kv_mask`` is False are not yet populated.
          kv_mask: ``(L,)`` validity mask.
        """
        q = split_heads(self.query_p(query), self.n_head)
        k = split_heads(k_cache, self.n_head)
        v = split_heads(v_cache, self.n_head)
        y = multi_head_attention(q, k, v, kv_mask=kv_mask)
        return self.proj(merge_heads(y))

    def attend_block(self, query: jax.Array, k_cache: jax.Array, v_cache: jax.Array, qk_mask: jax.Array) -> jax.Array:
        """Attention for a window of query positions over a static-length cache.

        The K=1 window with ``qk_mask = valid[None]`` is numerically the same
        program as :meth:`attend_cached` — the speculative decode relies on
        the per-row results matching the sequential path bit-for-bit.

        Args:
          query: ``(B, K, D)`` un-projected query inputs.
          k_cache / v_cache: ``(B, L, D)`` raw projections.
          qk_mask: ``(K, L)`` or ``(B, K, L)`` per-query validity mask (row
            j's causal frontier within the cache).
        """
        q = split_heads(self.query_p(query), self.n_head)
        k = split_heads(k_cache, self.n_head)
        v = split_heads(v_cache, self.n_head)
        y = multi_head_attention(q, k, v, qk_mask=qk_mask)
        return self.proj(merge_heads(y))


class MlpBlock(nn.Module):
    """The transformer block MLP: Linear-GELU-Linear (``ma_transformer.py:83-87``)."""

    n_embd: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = dense(self.n_embd, gain=GAIN_ACT, dtype=self.dtype)(x)
        x = gelu(x)
        return dense(self.n_embd, dtype=self.dtype)(x)


class EncodeBlock(nn.Module):
    """Post-LN residual encoder block, unmasked attention (``ma_transformer.py:72-92``)."""

    n_embd: int
    n_head: int
    dtype: Optional[jnp.dtype] = None

    def setup(self):
        self.ln1 = nn.LayerNorm(dtype=self.dtype)
        self.ln2 = nn.LayerNorm(dtype=self.dtype)
        self.attn = SelfAttention(self.n_embd, self.n_head, masked=False, dtype=self.dtype)
        self.mlp = MlpBlock(self.n_embd, dtype=self.dtype)

    def __call__(self, x: jax.Array) -> jax.Array:
        x = self.ln1(x + self.attn(x, x, x))
        x = self.ln2(x + self.mlp(x))
        return x


class DecodeBlock(nn.Module):
    """Decoder block: causal self-attn over shifted actions, then causal
    cross-attn with the encoder representation as query
    (``ma_transformer.py:95-116``)."""

    n_embd: int
    n_head: int
    dtype: Optional[jnp.dtype] = None

    def setup(self):
        self.ln1 = nn.LayerNorm(dtype=self.dtype)
        self.ln2 = nn.LayerNorm(dtype=self.dtype)
        self.ln3 = nn.LayerNorm(dtype=self.dtype)
        self.attn1 = SelfAttention(self.n_embd, self.n_head, masked=True, dtype=self.dtype)
        self.attn2 = SelfAttention(self.n_embd, self.n_head, masked=True, dtype=self.dtype)
        self.mlp = MlpBlock(self.n_embd, dtype=self.dtype)

    def __call__(self, x: jax.Array, rep_enc: jax.Array) -> jax.Array:
        x = self.ln1(x + self.attn1(x, x, x))
        x = self.ln2(rep_enc + self.attn2(key=x, value=x, query=rep_enc))
        x = self.ln3(x + self.mlp(x))
        return x

    def decode_step(self, x: jax.Array, rep_i: jax.Array, cache: dict, i: jax.Array):
        """Single-position decode with KV caches.

        Args:
          x: ``(B, 1, D)`` this position's input embedding.
          rep_i: ``(B, 1, D)`` encoder representation at position i.
          cache: dict with ``k1, v1, k2, v2`` each ``(B, L, D)``.
          i: scalar position index.

        Returns:
          ``(B, 1, D)`` block output and the updated cache.
        """
        L = cache["k1"].shape[1]
        valid = jnp.arange(L) <= i

        k1, v1 = self.attn1.project_kv(x)
        cache = dict(cache)
        cache["k1"] = jax.lax.dynamic_update_slice(cache["k1"], k1, (0, i, 0))
        cache["v1"] = jax.lax.dynamic_update_slice(cache["v1"], v1, (0, i, 0))
        y = self.attn1.attend_cached(x, cache["k1"], cache["v1"], valid)
        h = self.ln1(x + y)

        k2, v2 = self.attn2.project_kv(h)
        cache["k2"] = jax.lax.dynamic_update_slice(cache["k2"], k2, (0, i, 0))
        cache["v2"] = jax.lax.dynamic_update_slice(cache["v2"], v2, (0, i, 0))
        y2 = self.attn2.attend_cached(rep_i, cache["k2"], cache["v2"], valid)
        h2 = self.ln2(rep_i + y2)

        return self.ln3(h2 + self.mlp(h2)), cache

    def decode_step_packed(self, x: jax.Array, rep_i: jax.Array,
                           q2_i: jax.Array, kv, layer: int, i: jax.Array,
                           valid: jax.Array):
        """Single-position decode against the packed head-split KV cache.

        The O(1)-per-step layout: K/V live pre-head-split in two stacked
        ``(n_layers, B, H, A, Dh)`` buffers (this block owns planes ``layer``
        for attn1 and ``layer + 1`` for attn2), each step writes one
        ``dynamic_update_slice`` column per plane and attends against the
        buffer directly — no per-step whole-cache ``split_heads`` transpose,
        and the cross-attn query ``q2_i`` arrives pre-projected (hoisted out
        of the scan by ``Decoder.decode_queries``).  Bit-exact to
        :meth:`decode_step` (tests/test_cached_decode.py).

        Args:
          x: ``(B, 1, D)`` this position's input embedding.
          rep_i: ``(B, 1, D)`` encoder representation at position i.
          q2_i: ``(B, H, 1, Dh)`` pre-projected cross-attn query at i.
          kv: ``(k_buf, v_buf)`` each ``(n_layers, B, H, A, Dh)``.
          layer: static plane index of this block's attn1 (attn2 = layer + 1).
          i: scalar position index.
          valid: ``(A,)`` mask, True at positions ``<= i``.

        Returns:
          ``(B, 1, D)`` block output and the updated ``(k_buf, v_buf)``.
        """
        k_buf, v_buf = kv
        k1h, v1h = self.attn1.project_kv_heads(x)
        k_buf = jax.lax.dynamic_update_slice(k_buf, k1h[None], (layer, 0, 0, i, 0))
        v_buf = jax.lax.dynamic_update_slice(v_buf, v1h[None], (layer, 0, 0, i, 0))
        q1 = self.attn1.project_q_heads(x)
        y = self.attn1.attend_heads(q1, k_buf[layer], v_buf[layer], valid)
        h = self.ln1(x + y)

        k2h, v2h = self.attn2.project_kv_heads(h)
        k_buf = jax.lax.dynamic_update_slice(k_buf, k2h[None], (layer + 1, 0, 0, i, 0))
        v_buf = jax.lax.dynamic_update_slice(v_buf, v2h[None], (layer + 1, 0, 0, i, 0))
        y2 = self.attn2.attend_heads(q2_i, k_buf[layer + 1], v_buf[layer + 1], valid)
        h2 = self.ln2(rep_i + y2)

        return self.ln3(h2 + self.mlp(h2)), (k_buf, v_buf)

    def decode_block(self, x: jax.Array, rep_w: jax.Array, cache: dict, start: jax.Array):
        """Windowed multi-position decode with KV caches (speculative decode).

        Processes ``K`` consecutive positions ``[start, start + K)`` in one
        pass: cache rows for the window are (re)written from the current
        draft inputs, and each query row ``j`` attends with its own causal
        frontier ``<= start + j`` — so a row whose in-window context is
        already correct produces exactly the ``decode_step`` output.

        Args:
          x: ``(B, K, D)`` the window's input embeddings.
          rep_w: ``(B, K, D)`` encoder representation over the window.
          cache: dict with ``k1, v1, k2, v2`` each ``(B, L, D)``; the caller
            guarantees ``start + K <= L``.
          start: scalar window start index, or ``(B,)`` per-row starts (the
            speculative decode advances each batch row independently).

        Returns:
          ``(B, K, D)`` block outputs and the updated cache.
        """
        L = cache["k1"].shape[1]
        K = x.shape[1]
        start = jnp.asarray(start)
        if start.ndim == 0:
            qk = jnp.arange(L)[None, :] <= (start + jnp.arange(K))[:, None]

            def put(buf, val):
                return jax.lax.dynamic_update_slice(buf, val, (0, start, 0))
        else:
            rows = jnp.arange(x.shape[0])[:, None]
            idx = start[:, None] + jnp.arange(K)                   # (B, K)
            qk = jnp.arange(L)[None, None, :] <= idx[..., None]    # (B, K, L)

            def put(buf, val):
                return buf.at[rows, idx].set(val)

        k1, v1 = self.attn1.project_kv(x)
        cache = dict(cache)
        cache["k1"] = put(cache["k1"], k1)
        cache["v1"] = put(cache["v1"], v1)
        y = self.attn1.attend_block(x, cache["k1"], cache["v1"], qk)
        h = self.ln1(x + y)

        k2, v2 = self.attn2.project_kv(h)
        cache["k2"] = put(cache["k2"], k2)
        cache["v2"] = put(cache["v2"], v2)
        y2 = self.attn2.attend_block(rep_w, cache["k2"], cache["v2"], qk)
        h2 = self.ln2(rep_w + y2)

        return self.ln3(h2 + self.mlp(h2)), cache


def init_decode_cache(n_block: int, batch: int, length: int, n_embd: int, dtype=jnp.float32):
    """Fresh per-block KV caches for autoregressive decoding."""
    blk = lambda: {k: jnp.zeros((batch, length, n_embd), dtype) for k in ("k1", "v1", "k2", "v2")}
    return [blk() for _ in range(n_block)]


def init_packed_cache(n_block: int, batch: int, length: int, n_embd: int,
                      n_head: int, dtype=jnp.float32):
    """Fresh packed head-split KV cache for the O(1) cached decode.

    One stacked ``(2 * n_block, B, H, A, Dh)`` buffer per K/V — two attention
    planes per decoder block (attn1 self-attn, attn2 cross-attn).  Fixed shape
    per batch bucket; each decode step writes one column per plane with
    ``dynamic_update_slice``.
    """
    shape = (2 * n_block, batch, n_head, length, n_embd // n_head)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def packed_cache_bytes(n_block: int, batch: int, length: int, n_embd: int,
                       dtype=jnp.float32) -> int:
    """Host-side size of one :func:`init_packed_cache` allocation (K + V)."""
    itemsize = jnp.dtype(dtype).itemsize
    return 2 * (2 * n_block) * batch * length * n_embd * itemsize
