"""Feature-extractor bases for the actor-critic (non-transformer) family.

JAX equivalents of ``mat/algorithms/utils/{mlp,cnn,rnn}.py``:

- ``MLPBase`` — optional input LayerNorm, then two ``MLPLayer`` stacks
  (Linear-act-LayerNorm x (1 + layer_N) each, ``mlp.py:8-30,33-67``).  For the
  DCML mixed action space the second stack widens to emit the full logit
  vector the ACT head slices (``mlp.py:51-56``).
- ``CNNBase`` — conv + 2 linear layers on image obs, inputs scaled by 1/255
  (``cnn.py:11-44``).
- ``GRULayer`` — ``recurrent_N`` stacked GRU cells with mask-gated hidden
  state and output LayerNorm (``rnn.py:7-80``).  The reference's
  segment-batching over zero-mask boundaries (``rnn.py:40-74``) is a CPU-side
  optimization of exactly "multiply hidden by mask each step"; here the
  sequence form is a ``lax.scan`` doing that multiply, which XLA pipelines.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ORTHO_GAIN_RELU = math.sqrt(2.0)         # nn.init.calculate_gain('relu')
ORTHO_GAIN_TANH = 5.0 / 3.0              # nn.init.calculate_gain('tanh')


def _dense(features: int, gain: float, use_bias: bool = True) -> nn.Dense:
    return nn.Dense(
        features,
        use_bias=use_bias,
        kernel_init=nn.initializers.orthogonal(gain),
        bias_init=nn.initializers.zeros_init(),
    )


class MLPLayer(nn.Module):
    """Linear-act-LayerNorm, then ``layer_N`` hidden repeats (``mlp.py:8-30``)."""

    hidden_size: int
    layer_N: int = 1
    use_relu: bool = True
    out_dim: Optional[int] = None  # width of the final repeat (mixed-action head)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = nn.relu if self.use_relu else nn.tanh
        gain = ORTHO_GAIN_RELU if self.use_relu else ORTHO_GAIN_TANH
        # When out_dim is set every layer is out_dim wide: the reference
        # passes out_dim as MLPLayer's hidden_size, so fc1 already widens and
        # the layer_N repeats stay wide (mlp.py:20-25,51-56).
        widths = [self.hidden_size if self.out_dim is None else self.out_dim] * (1 + self.layer_N)
        for w in widths:
            x = _dense(w, gain)(x)
            x = act(x)
            x = nn.LayerNorm()(x)
        return x


class MLPBase(nn.Module):
    """Two stacked ``MLPLayer``s with optional feature normalization
    (``mlp.py:33-67``).  ``out_dim`` (set for mixed action spaces) widens the
    output stack so the ACT head can slice logits directly."""

    hidden_size: int
    layer_N: int = 1
    use_relu: bool = True
    use_feature_normalization: bool = True
    out_dim: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.use_feature_normalization:
            x = nn.LayerNorm()(x)
        x = MLPLayer(self.hidden_size, self.layer_N, self.use_relu)(x)
        x = MLPLayer(self.hidden_size, self.layer_N, self.use_relu, out_dim=self.out_dim)(x)
        return x


class CNNBase(nn.Module):
    """Conv-flatten-linear-linear on (C, H, W) image obs (``cnn.py:11-58``)."""

    hidden_size: int
    use_relu: bool = True
    kernel_size: int = 3
    stride: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = nn.relu if self.use_relu else nn.tanh
        gain = ORTHO_GAIN_RELU if self.use_relu else ORTHO_GAIN_TANH
        x = x / 255.0
        # NCHW -> NHWC for lax conv defaults.
        x = jnp.moveaxis(x, -3, -1)
        x = nn.Conv(
            self.hidden_size // 2,
            kernel_size=(self.kernel_size, self.kernel_size),
            strides=(self.stride, self.stride),
            padding="VALID",
            kernel_init=nn.initializers.orthogonal(gain),
            bias_init=nn.initializers.zeros_init(),
        )(x)
        x = act(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = act(_dense(self.hidden_size, gain)(x))
        x = act(_dense(self.hidden_size, gain)(x))
        return x


class GRULayer(nn.Module):
    """Mask-gated stacked GRU with output LayerNorm (``rnn.py:7-80``).

    Hidden state layout: ``(batch, recurrent_N, hidden)``.  A zero mask at
    step t resets the hidden state before the cell runs — identical semantics
    to the reference's ``hxs * masks`` pre-multiply (``rnn.py:27-28,66``).
    """

    hidden_size: int
    recurrent_N: int = 1

    def setup(self):
        self.cells = [
            nn.GRUCell(
                self.hidden_size,
                kernel_init=nn.initializers.orthogonal(),
                recurrent_kernel_init=nn.initializers.orthogonal(),
                bias_init=nn.initializers.zeros_init(),
            )
            for _ in range(self.recurrent_N)
        ]
        self.norm = nn.LayerNorm()

    def __call__(self, x: jax.Array, hxs: jax.Array, masks: jax.Array):
        """Single step: ``x`` (B, d), ``hxs`` (B, N, h), ``masks`` (B, 1)."""
        new_h = []
        for i, cell in enumerate(self.cells):
            h = hxs[:, i] * masks
            h, x = cell(h, x)
            new_h.append(h)
        return self.norm(x), jnp.stack(new_h, axis=1)

    def run_sequence(self, xs: jax.Array, hxs: jax.Array, masks: jax.Array):
        """Sequence form: ``xs`` (T, B, d), ``hxs`` (B, N, h), ``masks`` (T, B, 1).

        Returns ``(T, B, h)`` outputs and the final hidden state.  Equivalent
        to the reference's flattened (T*B) path (``rnn.py:31-74``).
        """

        def body(mdl, h, inp):
            x_t, m_t = inp
            out, h = mdl(x_t, h, m_t)
            return h, out

        # nn.scan (not raw lax.scan over the bound module): flax forbids
        # calling submodules from a different trace level than they were
        # bound at; params broadcast across steps, no per-step rngs
        final_h, outs = nn.scan(
            body, variable_broadcast="params", split_rngs={"params": False}
        )(self, hxs, (xs, masks))
        return outs, final_h
