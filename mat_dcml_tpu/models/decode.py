"""Autoregressive and teacher-forced action machinery for MAT.

TPU-native replacement for ``mat_src/mat/algorithms/utils/transformer_act.py``.
The reference's Python loop of full decoder forwards (one per agent,
``transformer_act.py:77-98``) becomes a single ``lax.scan`` over agents with
per-block KV caches — O(L) cached attention per step instead of O(L^2) full
recompute, all inside one compiled program.

The reference's "stride" batched decode (``transformer_act.py:37-75,138-158``)
— an approximation that commits blocks of agents from one decoder pass so the
GPU does fewer kernel launches — is kept as ``stride_decode`` for benchmark
protocol parity, but on TPU the exact scan decode is the default everywhere.

All functions are pure: ``params`` in, arrays out.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.mat import (
    AVAILABLE_CONTINUOUS,
    CONTINUOUS,
    DISCRETE,
    SEMI_DISCRETE,
    MATConfig,
    MultiAgentTransformer,
    NORMAL_STD,
)
from mat_dcml_tpu.ops import distributions as D
from mat_dcml_tpu.telemetry.scopes import named_scope, probe


class DecodeResult(NamedTuple):
    action: jax.Array       # (B, n_agent, act_out) float32
    log_prob: jax.Array     # (B, n_agent, act_prob) float32


class SpecStats(NamedTuple):
    """Per-row accounting from one :func:`spec_decode` call (all ``(B,)``
    float32).  ``draft_passes`` is THE number that replaces ``n_agent``
    sequential decoder steps — mean accepted block length K̄ =
    ``n_agent / draft_passes``."""

    draft_passes: jax.Array     # decoder block passes (each drafts a window)
    verify_passes: jax.Array    # passes that checked >=1 outstanding draft
    drafts_offered: jax.Array   # draft positions subject to verification
    drafts_accepted: jax.Array  # drafts confirmed exact and committed


# "auto" = XLA.  DECIDED (round 4, BENCHLOG "whole-decode kernel: decided"):
# the only on-chip measurement of record (r3 session 1) put the XLA decode
# scan at 3 µs/position — far below any regime where a fused kernel matters
# — so the whole-decode Pallas kernel (ops/pallas_decode.py) is a documented
# PORTABILITY ARTIFACT, selectable via MAT_DCML_TPU_DECODE_IMPL=pallas and
# kept interpret-mode parity-tested, not the default.  Revisit only if a
# future measured on-chip A/B shows a win.
_DECODE_IMPL_ENV = "MAT_DCML_TPU_DECODE_IMPL"
_VALID_DECODE_IMPLS = ("auto", "xla", "pallas", "pallas_interpret")

# Permanently False absent a measured on-chip win (see above); kill switch
# for experiments: MAT_DCML_TPU_DECODE_IMPL=xla.
_AUTO_PALLAS_ON_TPU = False


def _resolve_decode_impl(cfg) -> str:
    impl = os.environ.get(_DECODE_IMPL_ENV, "auto")
    if impl not in _VALID_DECODE_IMPLS:
        raise ValueError(
            f"{_DECODE_IMPL_ENV} must be one of {_VALID_DECODE_IMPLS}, got {impl!r}"
        )
    if cfg.dec_actor:
        return "xla"               # MAT-Dec has no decoder trunk to fuse
    if impl == "auto":
        if (
            _AUTO_PALLAS_ON_TPU
            and jax.default_backend() == "tpu"
            and cfg.action_type in (DISCRETE, SEMI_DISCRETE)
        ):
            return "pallas"
        return "xla"
    return impl


def _action_std(model: MultiAgentTransformer, params) -> jax.Array:
    return model.apply(params, method="action_std")


# ---------------------------------------------------------------------------
# Params-only serving entry (shared by training rollout and serving/engine)
# ---------------------------------------------------------------------------

DECODE_MODES = ("scan", "stride", "spec", "cached")


def serve_decode(
    cfg: MATConfig,
    params,
    key: jax.Array,
    state: jax.Array,
    obs: jax.Array,
    available_actions: Optional[jax.Array] = None,
    deterministic: bool = True,
    mode: str = "scan",
    stride: int = 2,
    spec_block: int = 8,
    return_spec_stats: bool = False,
):
    """One params-only signature for the full encode+decode forward.

    This is the seam serving and training share: ``policy.get_actions`` /
    ``policy.act_stride`` and ``serving/engine.py`` all route through here, so
    the served action path IS the training rollout path (parity pinned by
    tests/test_serving.py).  Everything non-array is static — ``cfg`` is a
    frozen hashable dataclass (MATConfig round-trips through
    ``training/checkpoint.export_policy``), and the model module is
    constructed *inside* from ``cfg`` alone, so a jit/AOT-lowered closure over
    this function captures no module state and donated caches stay legal.

    ``mode``: ``"scan"`` = exact single-scan autoregressive decode with
    per-block KV caches (:func:`ar_decode`); ``"stride"`` = the reference's
    block-commit approximation (:func:`stride_decode`, deterministic only —
    ``deterministic=False`` raises, there is no stochastic stride sampling
    path); ``"spec"`` = draft-verify speculative decode (:func:`spec_decode`),
    bit-exact to ``"scan"`` for both deterministic and stochastic decode with
    ~A/K̄ decoder passes; ``"cached"`` = O(1)-per-step decode against the
    packed head-split KV cache (:func:`cached_decode`), bit-exact to
    ``"scan"`` including log-probs and the gumbel key chain (``dec_actor``
    has no decoder trunk to cache and silently falls back to the scan path,
    which is already step-minimal there).  ``key`` is always taken (ignored
    by deterministic paths) so all modes present the same call signature to
    AOT compilation.

    Returns ``(values, DecodeResult)``; with ``return_spec_stats=True``
    (``mode="spec"`` only) returns ``(values, DecodeResult, SpecStats)``.
    """
    if mode not in DECODE_MODES:
        raise ValueError(f"mode must be one of {DECODE_MODES}, got {mode!r}")
    if mode == "stride" and not deterministic:
        raise ValueError(
            "decode mode 'stride' is deterministic-only (the reference's "
            "block-commit approximation has no stochastic sampling path); "
            "use mode='scan' or mode='spec' for stochastic decode"
        )
    if return_spec_stats and mode != "spec":
        raise ValueError(
            f"return_spec_stats requires mode='spec', got mode={mode!r}"
        )
    model = MultiAgentTransformer(cfg)
    v_loc, obs_rep = model.apply(params, state, obs, method="encode")
    if mode == "stride":
        res = stride_decode(
            model, params, obs_rep, obs, available_actions, stride=stride
        )
    elif mode == "spec":
        res, stats = spec_decode(
            model, params, key, obs_rep, available_actions, deterministic,
            block=spec_block,
        )
        if return_spec_stats:
            return v_loc, res, stats
    elif mode == "cached" and not cfg.dec_actor:
        res = cached_decode(
            model, params, key, obs_rep, available_actions, deterministic
        )
    else:
        res = ar_decode(
            model, params, key, obs_rep, obs, available_actions, deterministic
        )
    return v_loc, res


# ---------------------------------------------------------------------------
# Autoregressive decode (exact; scan + KV cache)
# ---------------------------------------------------------------------------

def ar_decode(
    model: MultiAgentTransformer,
    params,
    key: jax.Array,
    obs_rep: jax.Array,
    obs: jax.Array,
    available_actions: Optional[jax.Array],
    deterministic: bool = False,
) -> DecodeResult:
    """Exact autoregressive decode over the agent axis.

    Equivalent to the reference's stochastic path (one decoder pass per agent,
    ``transformer_act.py:76-99,159-173,192-216,244-283``) but compiled as one
    scan.  ``deterministic=True`` takes distribution modes (argmax / mean)
    with no block-commit approximation.
    """
    cfg = model.cfg
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim
    in_dim = cfg.action_input_dim

    impl = _resolve_decode_impl(cfg)
    if impl.startswith("pallas") and cfg.action_type in (DISCRETE, SEMI_DISCRETE):
        return _fused_ar_decode_path(
            model, params, key, obs_rep, available_actions, deterministic,
            interpret=impl == "pallas_interpret",
        )

    if available_actions is None:
        available_actions = jnp.ones((B, A, adim), jnp.float32)

    has_cont = cfg.action_type != DISCRETE
    std = _action_std(model, params) if has_cont else None

    start_token = jnp.zeros((B, 1, in_dim), jnp.float32)
    if cfg.action_type in (DISCRETE, SEMI_DISCRETE, AVAILABLE_CONTINUOUS):
        start_token = start_token.at[:, 0, 0].set(1.0)  # transformer_act.py:33

    # SEMI_DISCRETE gaussian-tail noise is precomputed at top level from the
    # scan's own key chain and consumed through the scan xs — the identical
    # arithmetic spec_decode replays, so the two decodes agree bit-for-bit
    # even stochastically (an in-scan draw compiles 1 ulp differently).
    tail_noise = jnp.zeros((A, B, adim), jnp.float32)
    if cfg.action_type == SEMI_DISCRETE and not deterministic:
        nd = cfg.n_discrete_agents
        if A - nd > 0:
            _, (_, kcs) = jax.lax.scan(
                lambda k, _: (lambda ks: (ks[0], (ks[1], ks[2])))(jax.random.split(k, 3)),
                key, None, length=A,
            )
            tail_noise = tail_noise.at[nd:].set(
                jax.vmap(lambda k: jax.random.normal(k, (B, adim), jnp.float32))(kcs[nd:])
            )

    caches = model.fresh_cache(B)

    if impl.startswith("pallas"):
        # continuous-family fallback: one fused kernel per decode position
        # (the discrete families take the whole-decode kernel path above)
        from mat_dcml_tpu.ops.pallas_decode import (
            fused_decode_step,
            pack_decode_weights,
        )

        fused_weights, _ = pack_decode_weights(params, cfg)
        cache_keys = ("k1", "v1", "k2", "v2")
        # the kernel holds KV caches position-major ((L, B, D) — Mosaic can't
        # lower the per-position write in (B, L, D) layout); fresh caches are
        # zeros, so the transpose folds away at trace time
        caches = [
            {k: jnp.swapaxes(c[k], 0, 1) for k in cache_keys} for c in caches
        ]

        def decode_step(caches, shifted_in, i):
            rep_i = jax.lax.dynamic_slice_in_dim(obs_rep, i, 1, axis=1)[:, 0]
            flat = [c[k] for c in caches for k in cache_keys]
            logits, new_flat = fused_decode_step(
                fused_weights, shifted_in[:, 0], rep_i, flat, i,
                n_head=cfg.n_head, adim=adim,
                interpret=impl == "pallas_interpret",
            )
            new_caches = [
                dict(zip(cache_keys, new_flat[4 * b : 4 * b + 4]))
                for b in range(cfg.n_block)
            ]
            return logits, new_caches
    else:
        def decode_step(caches, shifted_in, i):
            rep_i = jax.lax.dynamic_slice_in_dim(obs_rep, i, 1, axis=1)
            obs_i = jax.lax.dynamic_slice_in_dim(obs, i, 1, axis=1)
            logits, caches = model.apply(
                params, shifted_in, rep_i, obs_i, caches, i, method="decode_step"
            )
            return logits[:, 0], caches  # (B, adim)

    def body(carry, xs):
        i, noise_i = xs
        caches, shifted_in, key = carry
        key, k_d, k_c = jax.random.split(key, 3)
        logits, caches = decode_step(caches, shifted_in, i)
        ava_i = jax.lax.dynamic_slice_in_dim(available_actions, i, 1, axis=1)[:, 0]
        act, logp, nxt = _sample_position(
            cfg, logits, ava_i, i, noise_i, k_d, k_c, std, deterministic, B
        )
        return (caches, nxt, key), (act, logp)

    with named_scope("mat/ar_decode"):
        (_, _, _), (acts, logps) = jax.lax.scan(
            body, (caches, start_token, key), (jnp.arange(A), tail_noise)
        )
    # scan stacks on axis 0 -> (A, B, d); move agents to axis 1.
    action = jnp.swapaxes(acts, 0, 1)
    log_prob = jnp.swapaxes(logps, 0, 1)
    probe("mat/ar_decode", {"action": action, "log_prob": log_prob})
    return DecodeResult(action, log_prob)


def _fused_ar_decode_path(
    model: MultiAgentTransformer,
    params,
    key: jax.Array,
    obs_rep: jax.Array,
    available_actions: Optional[jax.Array],
    deterministic: bool,
    interpret: bool = False,
) -> DecodeResult:
    """Whole-decode fused kernel path (``ops/pallas_decode.fused_ar_decode``).

    Reproduces the XLA scan's draws: the per-position key chain
    (``key, k_d, k_c = split(key, 3)``) is replayed here, and
    ``jax.random.categorical(k, logits)`` == ``argmax(logits + gumbel(k,
    logits.shape))``, so precomputing the Gumbel tensor and arg-maxing inside
    the kernel is the same sample — up to the kernel's polynomial-erf gelu
    (~1e-4 logit tolerance; Mosaic has no erf primitive), so a draw can flip
    only when two gumbel-perturbed logits tie within that margin.  The
    semi-discrete Gaussian tail (``transformer_act.py:93-98``) likewise
    consumes precomputed normal noise.
    """
    from mat_dcml_tpu.ops.pallas_decode import (
        fused_ar_decode,
        pack_ar_decode_weights,
    )

    cfg = model.cfg
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim
    nd = cfg.n_discrete_agents if cfg.action_type == SEMI_DISCRETE else A
    n_rows = max(1, A - nd)

    def split_step(k, _):
        k, k_d, k_c = jax.random.split(k, 3)
        return k, (k_d, k_c)

    _, (kds, kcs) = jax.lax.scan(split_step, key, None, length=A)
    if deterministic:
        gumbel = jnp.zeros((B, A, adim), jnp.float32)
        normal = jnp.zeros((B, n_rows, adim), jnp.float32)
    else:
        gumbel = jnp.transpose(
            jax.vmap(lambda k: jax.random.gumbel(k, (B, adim), jnp.float32))(kds),
            (1, 0, 2),
        )
        if A - nd > 0:
            normal = jnp.transpose(
                jax.vmap(lambda k: jax.random.normal(k, (B, adim), jnp.float32))(kcs[nd:]),
                (1, 0, 2),
            )
        else:
            normal = jnp.zeros((B, n_rows, adim), jnp.float32)

    std = _action_std(model, params) if cfg.action_type != DISCRETE else None
    weights, _ = pack_ar_decode_weights(params, cfg, std)
    adim_pad = weights.embed_act.shape[0]
    pad = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, adim_pad - x.shape[2])))
    gumbel, normal = pad(gumbel), pad(normal)
    avail = (
        pad(available_actions.astype(jnp.float32))
        if available_actions is not None
        else None
    )
    act, logp = fused_ar_decode(
        weights, obs_rep, gumbel, normal, avail,
        n_head=cfg.n_head, adim=adim, nd=nd, interpret=interpret,
    )
    return DecodeResult(act[..., None], logp[..., None])


def _discrete_branch(logits, ava_i, key, deterministic, adim, in_dim):
    masked = D.mask_logits(logits, ava_i)
    idx = D.categorical_mode(masked) if deterministic else D.categorical_sample(key, masked)
    logp = D.categorical_log_prob(masked, idx)
    onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
    nxt = jnp.zeros((logits.shape[0], 1, in_dim), jnp.float32)
    nxt = nxt.at[:, 0, 1:].set(onehot)  # transformer_act.py:90
    return idx[:, None].astype(jnp.float32), logp[:, None], nxt


def _continuous_branch(mean, std, key, deterministic):
    act = mean if deterministic else D.normal_sample(key, mean, std)
    logp = D.normal_log_prob(mean, std, act)
    return act, logp


def _sample_position(cfg, logits, ava_i, i, noise_i, k_d, k_c, std, deterministic, B):
    """Per-position sampling shared by :func:`ar_decode` and
    :func:`cached_decode` — one body, so the two modes' gumbel/gaussian
    arithmetic cannot drift apart.  ``logits`` is ``(B, adim)`` for position
    ``i``; returns ``(act, logp, nxt)`` with ``nxt`` the next step's
    shifted-action feed ``(B, 1, action_input_dim)``.
    """
    adim, in_dim = cfg.action_dim, cfg.action_input_dim
    if cfg.action_type == DISCRETE:
        act, logp, nxt = _discrete_branch(logits, ava_i, k_d, deterministic, adim, in_dim)
    elif cfg.action_type == SEMI_DISCRETE:
        d_act, d_logp, d_nxt = _discrete_branch(logits, ava_i, k_d, deterministic, adim, in_dim)
        c_act = logits if deterministic else D.normal_sample_from_noise(logits, std, noise_i)
        c_logp = D.normal_log_prob(logits, std, c_act)
        is_cont = i >= cfg.n_discrete_agents
        act = jnp.where(is_cont, c_act[:, -1:], d_act)
        logp = jnp.where(is_cont, c_logp[:, -1:], d_logp)
        nxt = d_nxt  # the continuous agent is last; its feed is never used
    elif cfg.action_type == CONTINUOUS:
        act, logp = _continuous_branch(logits, std, k_c, deterministic)
        nxt = act[:, None, :]
    else:  # AVAILABLE_CONTINUOUS (transformer_act.py:244-283)
        dd = cfg.discrete_dim
        d_logits = D.mask_logits(logits[:, :dd], ava_i[:, :dd])
        d_idx = (
            D.categorical_mode(d_logits) if deterministic else D.categorical_sample(k_d, d_logits)
        )
        d_logp = D.categorical_log_prob(d_logits, d_idx)
        d_onehot = jax.nn.one_hot(d_idx, dd, dtype=jnp.float32)
        c_std = std[dd:]
        c_mean = logits[:, dd:]
        c_act = c_mean if deterministic else D.normal_sample(k_c, c_mean, c_std)
        c_logp = D.normal_log_prob(c_mean, c_std, c_act)
        act = jnp.concatenate([d_onehot, c_act], axis=-1)
        logp = jnp.concatenate([d_logp[:, None], c_logp], axis=-1)
        nxt = jnp.zeros((B, 1, in_dim), jnp.float32).at[:, 0, 1:].set(act)
    return act, logp, nxt


# ---------------------------------------------------------------------------
# Cached decode (exact; O(1) new work per step against a packed KV buffer)
# ---------------------------------------------------------------------------

def cached_decode(
    model: MultiAgentTransformer,
    params,
    key: jax.Array,
    obs_rep: jax.Array,
    available_actions: Optional[jax.Array],
    deterministic: bool = False,
) -> DecodeResult:
    """O(1)-per-step autoregressive decode, bit-exact to :func:`ar_decode`.

    The scan path re-derives per-step state the compiler cannot hoist: every
    position re-projects its cross-attn query from ``obs_rep`` and the raw
    ``(B, L, D)`` caches are head-split inside every attention.  This path
    restructures the decode around a packed cache so each step's *new* work
    is exactly one position:

      - K/V live pre-split in two stacked ``(2 * n_block, B, H, A, Dh)``
        buffers (``modules.init_packed_cache``) — plane ``2b`` is block b's
        self-attn, plane ``2b + 1`` its cross-attn — written with one
        ``dynamic_update_slice`` column per plane per step and attended with
        a ``position <= i`` mask.
      - Cross-attn queries for all A positions are hoisted out of the scan
        into one batched projection per block (``decode_queries``), since
        ``obs_rep`` is fully known before the loop starts.

    Bit-exactness rests on three XLA identities pinned in
    tests/test_cached_decode.py: batched-then-sliced dense == per-step dense
    on the slice; attention over a pre-split cache == split of the raw cache;
    and a head-split ``dynamic_update_slice`` == splitting the raw-updated
    buffer.  Sampling reuses :func:`_sample_position` and the scan's own
    ``key, k_d, k_c = split(key, 3)`` chain, so actions AND log-probs match
    ``mode="scan"`` bitwise, deterministic or stochastic.

    Raises for ``dec_actor`` (no decoder trunk to cache); ``serve_decode``
    falls back to the scan path for that ablation.
    """
    cfg = model.cfg
    if cfg.dec_actor:
        raise ValueError("cached_decode does not support dec_actor (no "
                         "decoder trunk to cache); use mode='scan'")
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim
    in_dim = cfg.action_input_dim

    if available_actions is None:
        available_actions = jnp.ones((B, A, adim), jnp.float32)

    has_cont = cfg.action_type != DISCRETE
    std = _action_std(model, params) if has_cont else None

    start_token = jnp.zeros((B, 1, in_dim), jnp.float32)
    if cfg.action_type in (DISCRETE, SEMI_DISCRETE, AVAILABLE_CONTINUOUS):
        start_token = start_token.at[:, 0, 0].set(1.0)  # transformer_act.py:33

    # identical tail-noise precompute to ar_decode (same key chain)
    tail_noise = jnp.zeros((A, B, adim), jnp.float32)
    if cfg.action_type == SEMI_DISCRETE and not deterministic:
        nd = cfg.n_discrete_agents
        if A - nd > 0:
            _, (_, kcs) = jax.lax.scan(
                lambda k, _: (lambda ks: (ks[0], (ks[1], ks[2])))(jax.random.split(k, 3)),
                key, None, length=A,
            )
            tail_noise = tail_noise.at[nd:].set(
                jax.vmap(lambda k: jax.random.normal(k, (B, adim), jnp.float32))(kcs[nd:])
            )

    kv = model.fresh_packed_cache(B)
    q2 = model.apply(params, obs_rep, method="decode_queries")  # (n_block,B,H,A,Dh)

    # per-position inputs ride the scan xs (leading-axis slicing is free)
    # instead of a dynamic_slice gather per step; transposes of identical
    # values, so bit-exactness vs the scan path's slices is preserved
    rep_x = jnp.swapaxes(obs_rep, 0, 1)[:, :, None, :]       # (A, B, 1, D)
    q2_x = jnp.moveaxis(q2, 3, 0)[:, :, :, :, None, :]       # (A, nb, B, H, 1, Dh)
    ava_x = jnp.swapaxes(available_actions, 0, 1)            # (A, B, adim)

    def body(carry, xs):
        i, noise_i, rep_i, q2_i, ava_i = xs
        kv, shifted_in, key = carry
        key, k_d, k_c = jax.random.split(key, 3)
        logits, kv = model.apply(
            params, shifted_in, rep_i, q2_i, kv, i, method="decode_step_cached"
        )
        act, logp, nxt = _sample_position(
            cfg, logits[:, 0], ava_i, i, noise_i, k_d, k_c, std, deterministic, B
        )
        return (kv, nxt, key), (act, logp)

    with named_scope("mat/cached_decode"):
        (_, _, _), (acts, logps) = jax.lax.scan(
            body, (kv, start_token, key),
            (jnp.arange(A), tail_noise, rep_x, q2_x, ava_x),
        )
    action = jnp.swapaxes(acts, 0, 1)
    log_prob = jnp.swapaxes(logps, 0, 1)
    probe("mat/cached_decode", {"action": action, "log_prob": log_prob})
    return DecodeResult(action, log_prob)


# ---------------------------------------------------------------------------
# Speculative decode (exact; draft-verify over the agent axis)
# ---------------------------------------------------------------------------

def spec_decode(
    model: MultiAgentTransformer,
    params,
    key: jax.Array,
    obs_rep: jax.Array,
    available_actions: Optional[jax.Array],
    deterministic: bool = False,
    block: int = 8,
) -> Tuple[DecodeResult, SpecStats]:
    """Draft-verify decode over the agent axis, bit-exact to :func:`ar_decode`.

    One ``lax.while_loop`` iteration runs ONE windowed decoder pass
    (``decode_block``: K consecutive positions against the per-block KV
    caches) that simultaneously *verifies* the outstanding drafts and
    *drafts* the next window — the Jacobi-fused form of draft-verify, so a
    decode costs ~``A / K̄`` decoder passes instead of ``A`` sequential steps.

    The state machine, per batch row (rows advance independently; a lockstep
    window would collapse K̄ to ~1 at collect batch sizes):

    1. window ``[s, s+K)`` with ``s = min(pos, A-K)``; feed inputs are the
       committed prefix's exact one-hots plus the previous pass's drafts.
    2. the pass yields logits for every window position; the action at each
       is ``argmax(masked_logits + gumbel)`` with gumbel noise *precomputed*
       from the same ``key, k_d, k_c = split(key, 3)`` chain as ``ar_decode``
       (the replay proven in :func:`_fused_ar_decode_path`), so sampling is a
       deterministic function of logits and acceptance is a pure
       logits-argmax comparison.
    3. position ``pos`` always commits (its feed context is fully committed,
       hence its logits are the exact sequential logits bit-for-bit — the
       windowed pass is bitwise-equal to ``decode_step``, pinned in
       tests/test_spec_decode.py); each following position commits while the
       chain of drafted feeds matches the exact actions.  The first mismatch
       position still commits — its logits were computed from the now-known-
       exact feeds — so every pass commits at least one position and a
       drifted draft can only cost speed, never correctness.
    4. committed cache rows were written from exact feeds and are never
       recomputed; draft rows are simply overwritten on the next pass.

    Exactness therefore needs no acceptance test on log-probs: committed
    logits are bitwise the sequential logits, and action, log-prob, and the
    gaussian tail (precomputed normal noise) are pure functions of them.

    Numerics caveat: on pathological parameter scales (every leaf ~N(1),
    including LayerNorm scales) the committed *log-probs* can drift +-1 ulp
    vs mode="scan" because XLA fuses the log-softmax differently in the two
    programs; actions remain exact (the argmax comparison is done on
    identical logits).  On realistic parameter scales the equality is
    bitwise — tests/test_spec_decode.py pins it including an adversarial
    near-zero-acceptance construction.

    Restrictions: DISCRETE / SEMI_DISCRETE trunks without ``dec_actor``
    (same family as ``stride_decode``); raises ``ValueError`` otherwise.

    Returns ``(DecodeResult, SpecStats)``.
    """
    cfg = model.cfg
    if cfg.action_type not in (DISCRETE, SEMI_DISCRETE):
        raise ValueError(
            "spec_decode supports DISCRETE/SEMI_DISCRETE action types, got "
            f"{cfg.action_type!r}; use mode='scan' for continuous families"
        )
    if cfg.dec_actor:
        raise ValueError("spec_decode does not support dec_actor (no decoder "
                         "trunk to speculate over); use mode='scan'")
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim
    in_dim = cfg.action_input_dim
    K = max(1, min(int(block), A))
    nd = cfg.n_discrete_agents if cfg.action_type == SEMI_DISCRETE else A
    has_cont = cfg.action_type == SEMI_DISCRETE

    if available_actions is None:
        available_actions = jnp.ones((B, A, adim), jnp.float32)
    std = _action_std(model, params) if has_cont else None

    # replay ar_decode's per-position key chain (see _fused_ar_decode_path)
    def split_step(k, _):
        k, k_d, k_c = jax.random.split(k, 3)
        return k, (k_d, k_c)

    _, (kds, kcs) = jax.lax.scan(split_step, key, None, length=A)
    if deterministic:
        gumbel = jnp.zeros((B, A, adim), jnp.float32)
        normal = jnp.zeros((B, A, adim), jnp.float32)
    else:
        gumbel = jnp.transpose(
            jax.vmap(lambda k: jax.random.gumbel(k, (B, adim), jnp.float32))(kds),
            (1, 0, 2),
        )
        normal = jnp.zeros((B, A, adim), jnp.float32)
        if has_cont and A - nd > 0:
            tail = jnp.transpose(
                jax.vmap(lambda k: jax.random.normal(k, (B, adim), jnp.float32))(kcs[nd:]),
                (1, 0, 2),
            )
            normal = normal.at[:, nd:].set(tail)

    rows = jnp.arange(B)[:, None]
    jj = jnp.arange(K)[None, :]
    # feed buffer has one scratch row: the write of window feeds lands at
    # [s+1, s+K] and must never clamp (a clamped dynamic scatter would shift
    # writes onto wrong positions); row A is write-only
    shifted0 = jnp.zeros((B, A + 1, in_dim), jnp.float32).at[:, 0, 0].set(1.0)

    def gather_w(buf, idx):
        return jnp.take_along_axis(buf, idx[..., None], axis=1)

    def body(c):
        pos = c["pos"]                                      # (B,)
        s = jnp.minimum(pos, A - K)                         # (B,)
        idx = s[:, None] + jnp.arange(K)                    # (B, K) global pos
        shifted_w = gather_w(c["shifted"], idx)             # (B, K, in_dim)
        rep_w = gather_w(obs_rep, idx)                      # (B, K, D)
        logits_w, caches = model.apply(
            params, shifted_w, rep_w, c["caches"], s, method="decode_block"
        )                                                   # (B, K, adim)

        masked = D.mask_logits(logits_w, gather_w(available_actions, idx))
        # == categorical_sample(k_d, masked) bitwise (gumbel replay); with
        # zero noise == categorical_mode(masked) (x + 0.0 preserves argmax)
        new_idx = jnp.argmax(masked + gather_w(gumbel, idx), axis=-1)  # (B, K)
        d_logp = D.categorical_log_prob(masked, new_idx)
        act_w = new_idx.astype(jnp.float32)
        logp_w = d_logp
        if has_cont:
            # gaussian tail: mean is the RAW logits (the ar_decode continuous
            # branch does not mask), noise precomputed per position
            c_act = (
                logits_w if deterministic
                else D.normal_sample_from_noise(logits_w, std, gather_w(normal, idx))
            )
            c_logp = D.normal_log_prob(logits_w, std, c_act)
            is_cont = idx >= nd
            act_w = jnp.where(is_cont, c_act[..., -1], act_w)
            logp_w = jnp.where(is_cont, c_logp[..., -1], logp_w)

        # acceptance chain: local j commits iff j == j0 (= pos - s, always
        # exact) or every drafted feed in [j0, j) matched the exact action
        j0 = (pos - s)[:, None]                             # (B, 1)
        drafted_w = jnp.take_along_axis(c["drafted"], idx, axis=1)
        m = jnp.where(jj >= j0, drafted_w == new_idx, True)  # (B, K)
        prefix = jnp.concatenate(
            [jnp.ones((B, 1), jnp.int32), jnp.cumprod(m.astype(jnp.int32), axis=1)[:, :-1]],
            axis=1,
        )                                                   # prod m[0..j-1]
        commit = (jj >= j0) & (prefix > 0)                  # (B, K)
        n_commit = commit.sum(axis=1)                       # (B,); 0 iff done

        def write_w(buf, vals):
            cur = jnp.take_along_axis(buf, idx, axis=1)
            return buf.at[rows, idx].set(jnp.where(commit, vals, cur))

        action = write_w(c["action"], act_w)
        log_prob = write_w(c["log_prob"], logp_w)
        # bookkeeping for the NEXT pass: every window position's current
        # candidate becomes its draft, and its one-hot feeds position g+1
        # (committed positions re-derive the identical values, so the
        # unconditional overwrite is bit-stable)
        drafted = c["drafted"].at[rows, idx].set(new_idx)
        feed = jnp.zeros((B, K, in_dim), jnp.float32).at[..., 1:].set(
            jax.nn.one_hot(new_idx, adim, dtype=jnp.float32)
        )
        shifted = c["shifted"].at[rows, idx + 1].set(feed)

        alive = (pos < A).astype(jnp.float32)
        offered = ((jj >= j0) & (drafted_w >= 0)).sum(axis=1).astype(jnp.float32)
        return dict(
            pos=pos + n_commit,
            shifted=shifted,
            drafted=drafted,
            action=action,
            log_prob=log_prob,
            caches=caches,
            draft_passes=c["draft_passes"] + alive,
            verify_passes=c["verify_passes"] + alive * (offered > 0),
            drafts_offered=c["drafts_offered"] + offered,
            drafts_accepted=c["drafts_accepted"]
            + jnp.maximum(n_commit - 1, 0).astype(jnp.float32),
        )

    carry = dict(
        pos=jnp.zeros((B,), jnp.int32),
        shifted=shifted0,
        drafted=jnp.full((B, A), -1, jnp.int32),
        action=jnp.zeros((B, A), jnp.float32),
        log_prob=jnp.zeros((B, A), jnp.float32),
        caches=model.fresh_cache(B),
        draft_passes=jnp.zeros((B,), jnp.float32),
        verify_passes=jnp.zeros((B,), jnp.float32),
        drafts_offered=jnp.zeros((B,), jnp.float32),
        drafts_accepted=jnp.zeros((B,), jnp.float32),
    )
    with named_scope("mat/spec_decode"):
        # every live row commits >= 1 position per pass, so the loop is
        # bounded by A iterations; trip count is dynamic but the program
        # shape is static (AOT serving compiles it once per bucket)
        carry = jax.lax.while_loop(lambda c: jnp.any(c["pos"] < A), body, carry)
    res = DecodeResult(carry["action"][..., None], carry["log_prob"][..., None])
    probe("mat/spec_decode", {"action": res.action, "log_prob": res.log_prob})
    stats = SpecStats(
        draft_passes=carry["draft_passes"],
        verify_passes=carry["verify_passes"],
        drafts_offered=carry["drafts_offered"],
        drafts_accepted=carry["drafts_accepted"],
    )
    return res, stats


def spec_accept_rate(stats: SpecStats) -> jax.Array:
    """Scalar accepted/offered in [0, 1] (1.0 when nothing was offered —
    a decode with A <= block that finished in pure-draft passes)."""
    offered = stats.drafts_offered.sum()
    return jnp.where(
        offered > 0, stats.drafts_accepted.sum() / jnp.maximum(offered, 1.0), 1.0
    )


# ---------------------------------------------------------------------------
# Teacher-forced parallel evaluation
# ---------------------------------------------------------------------------

def parallel_act(
    model: MultiAgentTransformer,
    params,
    obs_rep: jax.Array,
    obs: jax.Array,
    action: jax.Array,
    available_actions: Optional[jax.Array],
    decode_fn=None,
) -> Tuple[jax.Array, jax.Array]:
    """Teacher-forced log-probs and entropies in one decoder pass.

    Reference twins: ``discrete_parallel_act`` (``transformer_act.py:176-189``),
    ``semi_discrete_parallel_act`` (``:103-129``), ``continuous_parallel_act``
    (``:219-232``), ``available_continuous_parallel_act`` (``:285-322``).

    ``decode_fn`` overrides the decoder application (same signature as
    ``decode_full``: ``(shifted, obs_rep, obs) -> logits``) — the
    sequence-parallel path routes it through ``seq_sharded_call``.

    Returns ``(log_prob, entropy)`` each ``(B, n_agent, act_prob_dim)``.
    """
    cfg = model.cfg
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim

    decode = decode_fn or partial(model.apply, params, method="decode_full")

    if cfg.action_type == DISCRETE:
        idx = action[..., 0].astype(jnp.int32)
        onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
        shifted = _shift_with_start(onehot, B, A, adim)
        logits = decode(shifted, obs_rep, obs)
        logits = D.mask_logits(logits, available_actions)
        logp = D.categorical_log_prob(logits, idx)[..., None]
        ent = D.categorical_entropy(logits)[..., None]
        return logp, ent

    if cfg.action_type == SEMI_DISCRETE:
        nd = cfg.n_discrete_agents
        idx = action[:, :nd, 0].astype(jnp.int32)
        onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
        cont = jnp.broadcast_to(action[:, nd:, :], (B, A - nd, adim))
        action_all = jnp.concatenate([onehot, cont], axis=1)
        shifted = _shift_with_start(action_all, B, A, adim)
        logits = decode(shifted, obs_rep, obs)
        d_logits = logits[:, :nd]
        if available_actions is not None:
            d_logits = D.mask_logits(d_logits, available_actions[:, :nd])
        d_logp = D.categorical_log_prob(d_logits, idx)[..., None]
        d_ent = D.categorical_entropy(d_logits)[..., None]
        std = _action_std(model, params)
        c_mean = logits[:, nd:]
        c_logp = D.normal_log_prob(c_mean, std, jnp.broadcast_to(action[:, nd:, :], c_mean.shape))
        c_ent = jnp.broadcast_to(D.normal_entropy(c_mean, std), c_mean.shape)
        logp = jnp.concatenate([d_logp, c_logp[:, :, -1:]], axis=1)
        ent = jnp.concatenate([d_ent, c_ent[:, :, -1:]], axis=1)
        return logp, ent

    if cfg.action_type == CONTINUOUS:
        shifted = jnp.zeros((B, A, adim), jnp.float32).at[:, 1:].set(action[:, :-1])
        mean = decode(shifted, obs_rep, obs)
        std = _action_std(model, params)
        logp = D.normal_log_prob(mean, std, action)
        ent = jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape)
        return logp, ent

    # AVAILABLE_CONTINUOUS
    dd = cfg.discrete_dim
    shifted = _shift_with_start(action, B, A, adim)
    logits = decode(shifted, obs_rep, obs)
    if available_actions is not None:
        # Reference masks the full logits tensor, continuous means included
        # (transformer_act.py:295-296).
        logits = D.mask_logits(logits, available_actions)
    d_idx = jnp.argmax(action[:, :, :dd], axis=-1)
    d_logp = D.categorical_log_prob(logits[:, :, :dd], d_idx)[..., None]
    d_ent = D.categorical_entropy(logits[:, :, :dd])[..., None]
    std = _action_std(model, params)[dd:]
    c_mean = logits[:, :, dd:]
    c_act = action[:, :, dd:]
    c_logp = D.normal_log_prob(c_mean, std, c_act)
    c_ent = jnp.broadcast_to(D.normal_entropy(c_mean, std), c_mean.shape)
    logp = jnp.concatenate([d_logp, c_logp], axis=-1)
    ent = jnp.concatenate([d_ent, c_ent], axis=-1)
    return logp, ent


def _shift_with_start(action_all: jax.Array, B: int, A: int, adim: int) -> jax.Array:
    """Start token + right-shifted actions (``transformer_act.py:108-110``)."""
    shifted = jnp.zeros((B, A, adim + 1), jnp.float32)
    shifted = shifted.at[:, 0, 0].set(1.0)
    return shifted.at[:, 1:, 1:].set(action_all[:, :-1, :])


# ---------------------------------------------------------------------------
# Stride-batched deterministic decode (benchmark-protocol parity)
# ---------------------------------------------------------------------------

def stride_decode(
    model: MultiAgentTransformer,
    params,
    obs_rep: jax.Array,
    obs: jax.Array,
    available_actions: Optional[jax.Array],
    stride: int = 2,
) -> DecodeResult:
    """The reference's deterministic block-commit decode
    (``transformer_act.py:37-75``): decode agent 0 alone, then commit blocks of
    ``stride`` discrete agents per full decoder pass — agents inside a block do
    NOT see each other's actions — then the continuous tail one at a time.

    Kept for exact reproduction of the published benchmark protocol
    (``DCML_MAT_ALT_Benchmark.py:126`` uses stride=10); exact decode via
    ``ar_decode(deterministic=True)`` is strictly better on TPU.
    """
    cfg = model.cfg
    assert cfg.action_type in (DISCRETE, SEMI_DISCRETE), "stride decode is discrete-family only"
    B, A, adim = obs_rep.shape[0], cfg.n_agent, cfg.action_dim
    nd = cfg.n_discrete_agents if cfg.action_type == SEMI_DISCRETE else A
    std = _action_std(model, params) if cfg.action_type == SEMI_DISCRETE else None
    if available_actions is None:
        # synthesize the all-ones mask exactly like ar_decode, so the masked
        # branch below never special-cases a missing mask
        available_actions = jnp.ones((B, A, adim), jnp.float32)

    shifted = jnp.zeros((B, A, adim + 1), jnp.float32).at[:, 0, 0].set(1.0)
    action = jnp.zeros((B, A, 1), jnp.float32)
    log_prob = jnp.zeros((B, A, 1), jnp.float32)

    # Static block boundaries: [0,1), [1,1+stride), ... then singleton tail.
    bounds = [(0, 1)]
    s = 1
    while s < nd:
        e = min(s + stride, nd)
        bounds.append((s, e))
        s = e
    while s < A:
        bounds.append((s, s + 1))
        s += 1

    decode = partial(model.apply, params, method="decode_full")
    for (s, e) in bounds:
        logits = decode(shifted, obs_rep, obs)[:, s:e]
        if e <= nd:
            masked = D.mask_logits(logits, available_actions[:, s:e])
            idx = jnp.argmax(masked, axis=-1)                     # (B, e-s)
            logp = jnp.take_along_axis(jax.nn.log_softmax(masked, axis=-1), idx[..., None], axis=-1)
            action = action.at[:, s:e].set(idx[..., None].astype(jnp.float32))
            log_prob = log_prob.at[:, s:e].set(logp)
            onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
            upto = min(e + 1, A)
            shifted = shifted.at[:, s + 1 : upto, 1:].set(onehot[:, : upto - s - 1])
        else:
            mean = logits[:, 0]
            logp = D.normal_log_prob(mean, std, mean)
            action = action.at[:, s, 0].set(mean[:, -1])
            log_prob = log_prob.at[:, s, 0].set(logp[:, -1])
    return DecodeResult(action, log_prob)
