"""Autoregressive and teacher-forced action machinery for MAT.

TPU-native replacement for ``mat_src/mat/algorithms/utils/transformer_act.py``.
The reference's Python loop of full decoder forwards (one per agent,
``transformer_act.py:77-98``) becomes a single ``lax.scan`` over agents with
per-block KV caches — O(L) cached attention per step instead of O(L^2) full
recompute, all inside one compiled program.

The reference's "stride" batched decode (``transformer_act.py:37-75,138-158``)
— an approximation that commits blocks of agents from one decoder pass so the
GPU does fewer kernel launches — is kept as ``stride_decode`` for benchmark
protocol parity, but on TPU the exact scan decode is the default everywhere.

All functions are pure: ``params`` in, arrays out.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.mat import (
    AVAILABLE_CONTINUOUS,
    CONTINUOUS,
    DISCRETE,
    SEMI_DISCRETE,
    MATConfig,
    MultiAgentTransformer,
    NORMAL_STD,
)
from mat_dcml_tpu.ops import distributions as D
from mat_dcml_tpu.telemetry.scopes import named_scope, probe


class DecodeResult(NamedTuple):
    action: jax.Array       # (B, n_agent, act_out) float32
    log_prob: jax.Array     # (B, n_agent, act_prob) float32


# "auto" = XLA.  DECIDED (round 4, BENCHLOG "whole-decode kernel: decided"):
# the only on-chip measurement of record (r3 session 1) put the XLA decode
# scan at 3 µs/position — far below any regime where a fused kernel matters
# — so the whole-decode Pallas kernel (ops/pallas_decode.py) is a documented
# PORTABILITY ARTIFACT, selectable via MAT_DCML_TPU_DECODE_IMPL=pallas and
# kept interpret-mode parity-tested, not the default.  Revisit only if a
# future measured A/B (scripts/tpu_session4.sh leg 2) shows a win.
_DECODE_IMPL_ENV = "MAT_DCML_TPU_DECODE_IMPL"
_VALID_DECODE_IMPLS = ("auto", "xla", "pallas", "pallas_interpret")

# Permanently False absent a measured on-chip win (see above); kill switch
# for experiments: MAT_DCML_TPU_DECODE_IMPL=xla.
_AUTO_PALLAS_ON_TPU = False


def _resolve_decode_impl(cfg) -> str:
    impl = os.environ.get(_DECODE_IMPL_ENV, "auto")
    if impl not in _VALID_DECODE_IMPLS:
        raise ValueError(
            f"{_DECODE_IMPL_ENV} must be one of {_VALID_DECODE_IMPLS}, got {impl!r}"
        )
    if cfg.dec_actor:
        return "xla"               # MAT-Dec has no decoder trunk to fuse
    if impl == "auto":
        if (
            _AUTO_PALLAS_ON_TPU
            and jax.default_backend() == "tpu"
            and cfg.action_type in (DISCRETE, SEMI_DISCRETE)
        ):
            return "pallas"
        return "xla"
    return impl


def _action_std(model: MultiAgentTransformer, params) -> jax.Array:
    return model.apply(params, method="action_std")


# ---------------------------------------------------------------------------
# Params-only serving entry (shared by training rollout and serving/engine)
# ---------------------------------------------------------------------------

DECODE_MODES = ("scan", "stride")


def serve_decode(
    cfg: MATConfig,
    params,
    key: jax.Array,
    state: jax.Array,
    obs: jax.Array,
    available_actions: Optional[jax.Array] = None,
    deterministic: bool = True,
    mode: str = "scan",
    stride: int = 2,
) -> Tuple[jax.Array, DecodeResult]:
    """One params-only signature for the full encode+decode forward.

    This is the seam serving and training share: ``policy.get_actions`` /
    ``policy.act_stride`` and ``serving/engine.py`` all route through here, so
    the served action path IS the training rollout path (parity pinned by
    tests/test_serving.py).  Everything non-array is static — ``cfg`` is a
    frozen hashable dataclass (MATConfig round-trips through
    ``training/checkpoint.export_policy``), and the model module is
    constructed *inside* from ``cfg`` alone, so a jit/AOT-lowered closure over
    this function captures no module state and donated caches stay legal.

    ``mode``: ``"scan"`` = exact single-scan autoregressive decode with
    per-block KV caches (:func:`ar_decode`); ``"stride"`` = the reference's
    block-commit approximation (:func:`stride_decode`, deterministic only).
    ``key`` is always taken (ignored by the deterministic stride path) so the
    two modes present the same call signature to AOT compilation.

    Returns ``(values, DecodeResult)``.
    """
    if mode not in DECODE_MODES:
        raise ValueError(f"mode must be one of {DECODE_MODES}, got {mode!r}")
    model = MultiAgentTransformer(cfg)
    v_loc, obs_rep = model.apply(params, state, obs, method="encode")
    if mode == "stride":
        res = stride_decode(
            model, params, obs_rep, obs, available_actions, stride=stride
        )
    else:
        res = ar_decode(
            model, params, key, obs_rep, obs, available_actions, deterministic
        )
    return v_loc, res


# ---------------------------------------------------------------------------
# Autoregressive decode (exact; scan + KV cache)
# ---------------------------------------------------------------------------

def ar_decode(
    model: MultiAgentTransformer,
    params,
    key: jax.Array,
    obs_rep: jax.Array,
    obs: jax.Array,
    available_actions: Optional[jax.Array],
    deterministic: bool = False,
) -> DecodeResult:
    """Exact autoregressive decode over the agent axis.

    Equivalent to the reference's stochastic path (one decoder pass per agent,
    ``transformer_act.py:76-99,159-173,192-216,244-283``) but compiled as one
    scan.  ``deterministic=True`` takes distribution modes (argmax / mean)
    with no block-commit approximation.
    """
    cfg = model.cfg
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim
    in_dim = cfg.action_input_dim

    impl = _resolve_decode_impl(cfg)
    if impl.startswith("pallas") and cfg.action_type in (DISCRETE, SEMI_DISCRETE):
        return _fused_ar_decode_path(
            model, params, key, obs_rep, available_actions, deterministic,
            interpret=impl == "pallas_interpret",
        )

    if available_actions is None:
        available_actions = jnp.ones((B, A, adim), jnp.float32)

    has_cont = cfg.action_type != DISCRETE
    std = _action_std(model, params) if has_cont else None

    start_token = jnp.zeros((B, 1, in_dim), jnp.float32)
    if cfg.action_type in (DISCRETE, SEMI_DISCRETE, AVAILABLE_CONTINUOUS):
        start_token = start_token.at[:, 0, 0].set(1.0)  # transformer_act.py:33

    caches = model.fresh_cache(B)

    if impl.startswith("pallas"):
        # continuous-family fallback: one fused kernel per decode position
        # (the discrete families take the whole-decode kernel path above)
        from mat_dcml_tpu.ops.pallas_decode import (
            fused_decode_step,
            pack_decode_weights,
        )

        fused_weights, _ = pack_decode_weights(params, cfg)
        cache_keys = ("k1", "v1", "k2", "v2")
        # the kernel holds KV caches position-major ((L, B, D) — Mosaic can't
        # lower the per-position write in (B, L, D) layout); fresh caches are
        # zeros, so the transpose folds away at trace time
        caches = [
            {k: jnp.swapaxes(c[k], 0, 1) for k in cache_keys} for c in caches
        ]

        def decode_step(caches, shifted_in, i):
            rep_i = jax.lax.dynamic_slice_in_dim(obs_rep, i, 1, axis=1)[:, 0]
            flat = [c[k] for c in caches for k in cache_keys]
            logits, new_flat = fused_decode_step(
                fused_weights, shifted_in[:, 0], rep_i, flat, i,
                n_head=cfg.n_head, adim=adim,
                interpret=impl == "pallas_interpret",
            )
            new_caches = [
                dict(zip(cache_keys, new_flat[4 * b : 4 * b + 4]))
                for b in range(cfg.n_block)
            ]
            return logits, new_caches
    else:
        def decode_step(caches, shifted_in, i):
            rep_i = jax.lax.dynamic_slice_in_dim(obs_rep, i, 1, axis=1)
            obs_i = jax.lax.dynamic_slice_in_dim(obs, i, 1, axis=1)
            logits, caches = model.apply(
                params, shifted_in, rep_i, obs_i, caches, i, method="decode_step"
            )
            return logits[:, 0], caches  # (B, adim)

    def body(carry, i):
        caches, shifted_in, key = carry
        key, k_d, k_c = jax.random.split(key, 3)
        logits, caches = decode_step(caches, shifted_in, i)
        ava_i = jax.lax.dynamic_slice_in_dim(available_actions, i, 1, axis=1)[:, 0]

        if cfg.action_type == DISCRETE:
            act, logp, nxt = _discrete_branch(logits, ava_i, k_d, deterministic, adim, in_dim)
        elif cfg.action_type == SEMI_DISCRETE:
            d_act, d_logp, d_nxt = _discrete_branch(logits, ava_i, k_d, deterministic, adim, in_dim)
            c_act, c_logp = _continuous_branch(logits, std, k_c, deterministic)
            is_cont = i >= cfg.n_discrete_agents
            act = jnp.where(is_cont, c_act[:, -1:], d_act)
            logp = jnp.where(is_cont, c_logp[:, -1:], d_logp)
            nxt = d_nxt  # the continuous agent is last; its feed is never used
        elif cfg.action_type == CONTINUOUS:
            act, logp = _continuous_branch(logits, std, k_c, deterministic)
            nxt = act[:, None, :]
        else:  # AVAILABLE_CONTINUOUS (transformer_act.py:244-283)
            dd = cfg.discrete_dim
            d_logits = D.mask_logits(logits[:, :dd], ava_i[:, :dd])
            d_idx = (
                D.categorical_mode(d_logits) if deterministic else D.categorical_sample(k_d, d_logits)
            )
            d_logp = D.categorical_log_prob(d_logits, d_idx)
            d_onehot = jax.nn.one_hot(d_idx, dd, dtype=jnp.float32)
            c_std = std[dd:]
            c_mean = logits[:, dd:]
            c_act = c_mean if deterministic else D.normal_sample(k_c, c_mean, c_std)
            c_logp = D.normal_log_prob(c_mean, c_std, c_act)
            act = jnp.concatenate([d_onehot, c_act], axis=-1)
            logp = jnp.concatenate([d_logp[:, None], c_logp], axis=-1)
            nxt = jnp.zeros((B, 1, in_dim), jnp.float32).at[:, 0, 1:].set(act)
        return (caches, nxt, key), (act, logp)

    with named_scope("mat/ar_decode"):
        (_, _, _), (acts, logps) = jax.lax.scan(
            body, (caches, start_token, key), jnp.arange(A)
        )
    # scan stacks on axis 0 -> (A, B, d); move agents to axis 1.
    action = jnp.swapaxes(acts, 0, 1)
    log_prob = jnp.swapaxes(logps, 0, 1)
    probe("mat/ar_decode", {"action": action, "log_prob": log_prob})
    return DecodeResult(action, log_prob)


def _fused_ar_decode_path(
    model: MultiAgentTransformer,
    params,
    key: jax.Array,
    obs_rep: jax.Array,
    available_actions: Optional[jax.Array],
    deterministic: bool,
    interpret: bool = False,
) -> DecodeResult:
    """Whole-decode fused kernel path (``ops/pallas_decode.fused_ar_decode``).

    Reproduces the XLA scan's draws: the per-position key chain
    (``key, k_d, k_c = split(key, 3)``) is replayed here, and
    ``jax.random.categorical(k, logits)`` == ``argmax(logits + gumbel(k,
    logits.shape))``, so precomputing the Gumbel tensor and arg-maxing inside
    the kernel is the same sample — up to the kernel's polynomial-erf gelu
    (~1e-4 logit tolerance; Mosaic has no erf primitive), so a draw can flip
    only when two gumbel-perturbed logits tie within that margin.  The
    semi-discrete Gaussian tail (``transformer_act.py:93-98``) likewise
    consumes precomputed normal noise.
    """
    from mat_dcml_tpu.ops.pallas_decode import (
        fused_ar_decode,
        pack_ar_decode_weights,
    )

    cfg = model.cfg
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim
    nd = cfg.n_discrete_agents if cfg.action_type == SEMI_DISCRETE else A
    n_rows = max(1, A - nd)

    def split_step(k, _):
        k, k_d, k_c = jax.random.split(k, 3)
        return k, (k_d, k_c)

    _, (kds, kcs) = jax.lax.scan(split_step, key, None, length=A)
    if deterministic:
        gumbel = jnp.zeros((B, A, adim), jnp.float32)
        normal = jnp.zeros((B, n_rows, adim), jnp.float32)
    else:
        gumbel = jnp.transpose(
            jax.vmap(lambda k: jax.random.gumbel(k, (B, adim), jnp.float32))(kds),
            (1, 0, 2),
        )
        if A - nd > 0:
            normal = jnp.transpose(
                jax.vmap(lambda k: jax.random.normal(k, (B, adim), jnp.float32))(kcs[nd:]),
                (1, 0, 2),
            )
        else:
            normal = jnp.zeros((B, n_rows, adim), jnp.float32)

    std = _action_std(model, params) if cfg.action_type != DISCRETE else None
    weights, _ = pack_ar_decode_weights(params, cfg, std)
    adim_pad = weights.embed_act.shape[0]
    pad = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, adim_pad - x.shape[2])))
    gumbel, normal = pad(gumbel), pad(normal)
    avail = (
        pad(available_actions.astype(jnp.float32))
        if available_actions is not None
        else None
    )
    act, logp = fused_ar_decode(
        weights, obs_rep, gumbel, normal, avail,
        n_head=cfg.n_head, adim=adim, nd=nd, interpret=interpret,
    )
    return DecodeResult(act[..., None], logp[..., None])


def _discrete_branch(logits, ava_i, key, deterministic, adim, in_dim):
    masked = D.mask_logits(logits, ava_i)
    idx = D.categorical_mode(masked) if deterministic else D.categorical_sample(key, masked)
    logp = D.categorical_log_prob(masked, idx)
    onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
    nxt = jnp.zeros((logits.shape[0], 1, in_dim), jnp.float32)
    nxt = nxt.at[:, 0, 1:].set(onehot)  # transformer_act.py:90
    return idx[:, None].astype(jnp.float32), logp[:, None], nxt


def _continuous_branch(mean, std, key, deterministic):
    act = mean if deterministic else D.normal_sample(key, mean, std)
    logp = D.normal_log_prob(mean, std, act)
    return act, logp


# ---------------------------------------------------------------------------
# Teacher-forced parallel evaluation
# ---------------------------------------------------------------------------

def parallel_act(
    model: MultiAgentTransformer,
    params,
    obs_rep: jax.Array,
    obs: jax.Array,
    action: jax.Array,
    available_actions: Optional[jax.Array],
    decode_fn=None,
) -> Tuple[jax.Array, jax.Array]:
    """Teacher-forced log-probs and entropies in one decoder pass.

    Reference twins: ``discrete_parallel_act`` (``transformer_act.py:176-189``),
    ``semi_discrete_parallel_act`` (``:103-129``), ``continuous_parallel_act``
    (``:219-232``), ``available_continuous_parallel_act`` (``:285-322``).

    ``decode_fn`` overrides the decoder application (same signature as
    ``decode_full``: ``(shifted, obs_rep, obs) -> logits``) — the
    sequence-parallel path routes it through ``seq_sharded_call``.

    Returns ``(log_prob, entropy)`` each ``(B, n_agent, act_prob_dim)``.
    """
    cfg = model.cfg
    B = obs_rep.shape[0]
    A, adim = cfg.n_agent, cfg.action_dim

    decode = decode_fn or partial(model.apply, params, method="decode_full")

    if cfg.action_type == DISCRETE:
        idx = action[..., 0].astype(jnp.int32)
        onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
        shifted = _shift_with_start(onehot, B, A, adim)
        logits = decode(shifted, obs_rep, obs)
        logits = D.mask_logits(logits, available_actions)
        logp = D.categorical_log_prob(logits, idx)[..., None]
        ent = D.categorical_entropy(logits)[..., None]
        return logp, ent

    if cfg.action_type == SEMI_DISCRETE:
        nd = cfg.n_discrete_agents
        idx = action[:, :nd, 0].astype(jnp.int32)
        onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
        cont = jnp.broadcast_to(action[:, nd:, :], (B, A - nd, adim))
        action_all = jnp.concatenate([onehot, cont], axis=1)
        shifted = _shift_with_start(action_all, B, A, adim)
        logits = decode(shifted, obs_rep, obs)
        d_logits = logits[:, :nd]
        if available_actions is not None:
            d_logits = D.mask_logits(d_logits, available_actions[:, :nd])
        d_logp = D.categorical_log_prob(d_logits, idx)[..., None]
        d_ent = D.categorical_entropy(d_logits)[..., None]
        std = _action_std(model, params)
        c_mean = logits[:, nd:]
        c_logp = D.normal_log_prob(c_mean, std, jnp.broadcast_to(action[:, nd:, :], c_mean.shape))
        c_ent = jnp.broadcast_to(D.normal_entropy(c_mean, std), c_mean.shape)
        logp = jnp.concatenate([d_logp, c_logp[:, :, -1:]], axis=1)
        ent = jnp.concatenate([d_ent, c_ent[:, :, -1:]], axis=1)
        return logp, ent

    if cfg.action_type == CONTINUOUS:
        shifted = jnp.zeros((B, A, adim), jnp.float32).at[:, 1:].set(action[:, :-1])
        mean = decode(shifted, obs_rep, obs)
        std = _action_std(model, params)
        logp = D.normal_log_prob(mean, std, action)
        ent = jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape)
        return logp, ent

    # AVAILABLE_CONTINUOUS
    dd = cfg.discrete_dim
    shifted = _shift_with_start(action, B, A, adim)
    logits = decode(shifted, obs_rep, obs)
    if available_actions is not None:
        # Reference masks the full logits tensor, continuous means included
        # (transformer_act.py:295-296).
        logits = D.mask_logits(logits, available_actions)
    d_idx = jnp.argmax(action[:, :, :dd], axis=-1)
    d_logp = D.categorical_log_prob(logits[:, :, :dd], d_idx)[..., None]
    d_ent = D.categorical_entropy(logits[:, :, :dd])[..., None]
    std = _action_std(model, params)[dd:]
    c_mean = logits[:, :, dd:]
    c_act = action[:, :, dd:]
    c_logp = D.normal_log_prob(c_mean, std, c_act)
    c_ent = jnp.broadcast_to(D.normal_entropy(c_mean, std), c_mean.shape)
    logp = jnp.concatenate([d_logp, c_logp], axis=-1)
    ent = jnp.concatenate([d_ent, c_ent], axis=-1)
    return logp, ent


def _shift_with_start(action_all: jax.Array, B: int, A: int, adim: int) -> jax.Array:
    """Start token + right-shifted actions (``transformer_act.py:108-110``)."""
    shifted = jnp.zeros((B, A, adim + 1), jnp.float32)
    shifted = shifted.at[:, 0, 0].set(1.0)
    return shifted.at[:, 1:, 1:].set(action_all[:, :-1, :])


# ---------------------------------------------------------------------------
# Stride-batched deterministic decode (benchmark-protocol parity)
# ---------------------------------------------------------------------------

def stride_decode(
    model: MultiAgentTransformer,
    params,
    obs_rep: jax.Array,
    obs: jax.Array,
    available_actions: Optional[jax.Array],
    stride: int = 2,
) -> DecodeResult:
    """The reference's deterministic block-commit decode
    (``transformer_act.py:37-75``): decode agent 0 alone, then commit blocks of
    ``stride`` discrete agents per full decoder pass — agents inside a block do
    NOT see each other's actions — then the continuous tail one at a time.

    Kept for exact reproduction of the published benchmark protocol
    (``DCML_MAT_ALT_Benchmark.py:126`` uses stride=10); exact decode via
    ``ar_decode(deterministic=True)`` is strictly better on TPU.
    """
    cfg = model.cfg
    assert cfg.action_type in (DISCRETE, SEMI_DISCRETE), "stride decode is discrete-family only"
    B, A, adim = obs_rep.shape[0], cfg.n_agent, cfg.action_dim
    nd = cfg.n_discrete_agents if cfg.action_type == SEMI_DISCRETE else A
    std = _action_std(model, params) if cfg.action_type == SEMI_DISCRETE else None

    shifted = jnp.zeros((B, A, adim + 1), jnp.float32).at[:, 0, 0].set(1.0)
    action = jnp.zeros((B, A, 1), jnp.float32)
    log_prob = jnp.zeros((B, A, 1), jnp.float32)

    # Static block boundaries: [0,1), [1,1+stride), ... then singleton tail.
    bounds = [(0, 1)]
    s = 1
    while s < nd:
        e = min(s + stride, nd)
        bounds.append((s, e))
        s = e
    while s < A:
        bounds.append((s, s + 1))
        s += 1

    decode = partial(model.apply, params, method="decode_full")
    for (s, e) in bounds:
        logits = decode(shifted, obs_rep, obs)[:, s:e]
        if e <= nd:
            masked = D.mask_logits(logits, available_actions[:, s:e]) if available_actions is not None else logits
            idx = jnp.argmax(masked, axis=-1)                     # (B, e-s)
            logp = jnp.take_along_axis(jax.nn.log_softmax(masked, axis=-1), idx[..., None], axis=-1)
            action = action.at[:, s:e].set(idx[..., None].astype(jnp.float32))
            log_prob = log_prob.at[:, s:e].set(logp)
            onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
            upto = min(e + 1, A)
            shifted = shifted.at[:, s + 1 : upto, 1:].set(onehot[:, : upto - s - 1])
        else:
            mean = logits[:, 0]
            logp = D.normal_log_prob(mean, std, mean)
            action = action.at[:, s, 0].set(mean[:, -1])
            log_prob = log_prob.at[:, s, 0].set(logp[:, -1])
    return DecodeResult(action, log_prob)
