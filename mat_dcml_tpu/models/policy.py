"""Functional policy wrapper for the MAT family.

TPU-native equivalent of ``transformer_policy.py``: the reference wraps the
torch module with numpy<->torch glue, (batch*agent)<->(batch, agent) reshapes
and an Adam optimizer; here the policy is a pure-function bundle over a params
pytree — optimizer state lives with the trainer (optax), checkpointing with
Orbax.  All methods keep the ``(batch, n_agent, dim)`` layout throughout; the
reference's flatten/split round-trips (``transformer_policy.py:136-139``)
disappear under jit.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mat_dcml_tpu.models import decode as decode_lib
from mat_dcml_tpu.models.mat import (
    AVAILABLE_CONTINUOUS,
    CONTINUOUS,
    DISCRETE,
    SEMI_DISCRETE,
    MATConfig,
    MultiAgentTransformer,
)


class PolicyOutput(NamedTuple):
    value: jax.Array       # (B, n_agent, n_objective)
    action: jax.Array      # (B, n_agent, act_out)
    log_prob: jax.Array    # (B, n_agent, act_prob)


class TransformerPolicy:
    """Stateless method bundle; params are passed explicitly.

    Mirrors ``transformer_policy.py:116-241`` (get_actions / get_values /
    evaluate_actions / act) with explicit PRNG keys instead of global torch RNG.
    """

    def __init__(self, cfg: MATConfig, decode_mode: str = "scan", spec_block: int = 8):
        if decode_mode not in decode_lib.DECODE_MODES:
            raise ValueError(
                f"decode_mode must be one of {decode_lib.DECODE_MODES}, got {decode_mode!r}"
            )
        self.cfg = cfg
        self.decode_mode = decode_mode
        self.spec_block = spec_block
        self.model = MultiAgentTransformer(cfg)
        # optional context parallelism: when set (a Mesh with a "seq" axis),
        # the teacher-forced training forward ring-shards the agent axis
        # (parallel/seq_parallel.py); rollout decode stays replicated
        self.seq_mesh = None
        # act bookkeeping (transformer_policy.py:43-57)
        if cfg.action_type in (DISCRETE, SEMI_DISCRETE):
            self.act_out_dim = 1
            self.act_prob_dim = 1
        elif cfg.action_type == AVAILABLE_CONTINUOUS:
            self.act_out_dim = cfg.action_dim
            self.act_prob_dim = cfg.action_dim - cfg.discrete_dim + 1
        else:
            self.act_out_dim = cfg.action_dim
            self.act_prob_dim = cfg.action_dim

    # -- init ---------------------------------------------------------------

    def init_params(self, key: jax.Array):
        cfg = self.cfg
        state = jnp.zeros((1, cfg.n_agent, cfg.state_dim), jnp.float32)
        obs = jnp.zeros((1, cfg.n_agent, cfg.obs_dim), jnp.float32)
        shifted = jnp.zeros((1, cfg.n_agent, cfg.action_input_dim), jnp.float32)
        return self.model.init(key, state, obs, shifted)

    # -- rollout ------------------------------------------------------------

    def get_actions(
        self,
        params,
        key: jax.Array,
        state: jax.Array,
        obs: jax.Array,
        available_actions: Optional[jax.Array] = None,
        deterministic: bool = False,
    ) -> PolicyOutput:
        """Autoregressive decode (``ma_transformer.py:298-329``).

        Routes through :func:`decode.serve_decode` — the same params-only
        entry ``serving/engine.py`` compiles — so rollout and serving share
        one code path.  ``decode_mode="spec"`` swaps in the bit-exact
        speculative decoder; outputs are identical, only speed differs."""
        out, _ = self.get_actions_with_stats(
            params, key, state, obs, available_actions, deterministic
        )
        return out

    def get_actions_with_stats(
        self,
        params,
        key: jax.Array,
        state: jax.Array,
        obs: jax.Array,
        available_actions: Optional[jax.Array] = None,
        deterministic: bool = False,
    ) -> Tuple[PolicyOutput, Optional[decode_lib.SpecStats]]:
        """:meth:`get_actions` plus the speculative-decode telemetry.

        Returns ``(output, stats)`` where ``stats`` is a
        :class:`decode.SpecStats` when ``decode_mode == "spec"`` and ``None``
        otherwise (scan has no draft/verify structure to report)."""
        if self.decode_mode == "spec":
            v_loc, res, stats = decode_lib.serve_decode(
                self.cfg, params, key, state, obs, available_actions,
                deterministic=deterministic, mode="spec",
                spec_block=self.spec_block, return_spec_stats=True,
            )
            return PolicyOutput(v_loc, res.action, res.log_prob), stats
        v_loc, res = decode_lib.serve_decode(
            self.cfg, params, key, state, obs, available_actions,
            deterministic=deterministic, mode=self.decode_mode,
        )
        return PolicyOutput(v_loc, res.action, res.log_prob), None

    def act_stride(
        self,
        params,
        state: jax.Array,
        obs: jax.Array,
        available_actions: Optional[jax.Array] = None,
        stride: int = 2,
    ) -> PolicyOutput:
        """Deterministic stride-batched decode for benchmark-protocol parity
        (``transformer_policy.py:219-241`` with ``stride``)."""
        v_loc, res = decode_lib.serve_decode(
            self.cfg, params, jax.random.key(0), state, obs, available_actions,
            mode="stride", stride=stride,
        )
        return PolicyOutput(v_loc, res.action, res.log_prob)

    # -- training -----------------------------------------------------------

    def evaluate_actions(
        self,
        params,
        state: jax.Array,
        obs: jax.Array,
        action: jax.Array,
        available_actions: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Teacher-forced values, log-probs, entropies
        (``ma_transformer.py:257-295``).  Returns ``(values, log_prob,
        entropy)`` with entropy un-reduced ``(B, n_agent, act_prob)`` — the
        trainer applies active-mask weighting (``transformer_policy.py:212-215``).
        """
        if self.seq_mesh is not None:
            from mat_dcml_tpu.parallel.seq_parallel import seq_sharded_call

            v_loc, obs_rep = seq_sharded_call(
                self.model, params, self.seq_mesh, "encode", 2, state, obs
            )
            decode_fn = lambda shifted, rep, o: seq_sharded_call(  # noqa: E731
                self.model, params, self.seq_mesh, "decode_full", 1,
                shifted, rep, o,
            )
            logp, ent = decode_lib.parallel_act(
                self.model, params, obs_rep, obs, action, available_actions,
                decode_fn=decode_fn,
            )
            return v_loc, logp, ent
        v_loc, obs_rep = self.model.apply(params, state, obs, method="encode")
        logp, ent = decode_lib.parallel_act(
            self.model, params, obs_rep, obs, action, available_actions
        )
        return v_loc, logp, ent

    def get_values(self, params, state: jax.Array, obs: jax.Array) -> jax.Array:
        """Encoder-as-critic value prediction (``ma_transformer.py:331-339``)."""
        v_loc, _ = self.model.apply(params, state, obs, method="encode")
        return v_loc
