"""Action heads for the actor-critic family (``mat/algorithms/utils/act.py``).

One Flax module dispatching on the space descriptor type (the reference
dispatches on gym class *names*, ``act.py:18-68``):

- ``Discrete`` / plain ``DCMLActionSpace`` -> one Categorical linear head
  (gain 0.01), availability-masked logits (``distributions.py:56-70``).
- ``Box`` / ``DCMLActionSpace(extra=True)`` -> DiagGaussian: linear mean head
  + learned ``log_std`` with ``std = sigmoid(log_std / std_x) * std_y``
  (``distributions.py:95-116``).
- ``MultiDiscrete`` -> one Categorical head per sub-action (``act.py:55-61``).
- ``MultiBinary`` -> Bernoulli head (``act.py:52-54``; the reference's
  ``FixedBernoulli.log_probs`` is a broken ``super.log_prob`` access — fixed
  here, SURVEY.md §7 known defects).
- ``DCMLActionSpace(mixed=True)`` -> NO linear head: the base's wide output
  vector is sliced into ``n_sub`` categorical logit groups + Gaussian tail
  means (``act.py:83-105,157-195``; the base widening is ``mlp.py:51-56``).

Log-prob layout matches the reference exactly: Discrete (B,1); Box (B,dim)
un-summed per dim (``FixedNormal.log_probs``, ``distributions.py:33-36``);
MultiDiscrete (B,heads); mixed (B,1) summed over every part (``act.py:103``).
Entropy from ``evaluate`` is the reference's active-mask-weighted scalar,
including the mixed mode's ``/0.98`` rescale of both parts (``act.py:195``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mat_dcml_tpu.envs.spaces import (
    Box,
    DCMLActionSpace,
    Discrete,
    MixedRole,
    MultiBinary,
    MultiDiscrete,
)
from mat_dcml_tpu.ops import distributions as D

GAIN_ACT_HEAD = 0.01  # act.py passes gain=0.01 by convention (config.py gain default)


def _head(features: int, gain: float = GAIN_ACT_HEAD) -> nn.Dense:
    return nn.Dense(
        features,
        kernel_init=nn.initializers.orthogonal(gain),
        bias_init=nn.initializers.zeros_init(),
    )


def _masked_mean(x: jax.Array, active_masks: Optional[jax.Array]) -> jax.Array:
    """Reference entropy weighting: ``(ent * active).sum() / active.sum()``
    with broadcast over trailing dims (``act.py:171-176,215-222``)."""
    if active_masks is None:
        return x.mean()
    while active_masks.ndim < x.ndim:
        active_masks = active_masks[..., None]
    while active_masks.ndim > x.ndim:
        active_masks = active_masks.squeeze(-1)
    return (x * active_masks).sum() / jnp.clip(active_masks.sum(), min=1e-8)


class ACTLayer(nn.Module):
    """Samples / evaluates actions from actor features."""

    space: object
    std_x_coef: float = 1.0
    std_y_coef: float = 0.5

    def setup(self):
        sp = self.space
        if isinstance(sp, Discrete):
            self.action_head = _head(sp.n)
        elif isinstance(sp, Box):
            self.mean_head = _head(sp.dim)
            self.log_std = self.param(
                "log_std", lambda k: jnp.ones((sp.dim,)) * self.std_x_coef
            )
        elif isinstance(sp, MultiDiscrete):
            self.action_heads = [_head(n) for n in sp.nvec]
        elif isinstance(sp, MultiBinary):
            self.action_head = _head(sp.n)
        elif isinstance(sp, MixedRole):
            # Both heads exist for every agent; the per-row role flag (last
            # available_actions column) selects which one acts.  See
            # envs/spaces.py:MixedRole for why this keeps HAPPO/MAPPO/IPPO
            # parameter pytrees homogeneous across DCML's heterogeneous agents.
            if sp.cont_dim != 1:
                raise NotImplementedError(
                    "MixedRole stores (B, 1) actions; cont_dim must be 1"
                )
            self.action_head = _head(sp.n)
            self.mean_head = _head(sp.cont_dim)
            self.log_std = self.param(
                "log_std", lambda k: jnp.ones((sp.cont_dim,)) * self.std_x_coef
            )
        elif isinstance(sp, DCMLActionSpace):
            if sp.mixed:
                # No head: features sliced directly (act.py:83-105).
                self.log_std = self.param(
                    "log_std", lambda k: jnp.ones((sp.cont_dim,))
                )
            elif sp.extra:
                self.mean_head = _head(sp.cont_dim)
                self.log_std = self.param(
                    "log_std", lambda k: jnp.ones((sp.cont_dim,)) * self.std_x_coef
                )
            elif sp.multi_discrete:
                # The reference's Action_Space MULTI_DISCRETE branch
                # (act.py:36-43) iterates a scalar ``high - low`` and cannot
                # construct — a latent defect, not a working mode.  Refuse
                # loudly instead of silently building a single head.
                raise NotImplementedError(
                    "DCMLActionSpace(multi_discrete=True) without mixed=True has "
                    "no working reference semantics; use MultiDiscrete(nvec) or "
                    "mixed=True"
                )
            else:
                self.action_head = _head(sp.n)
        else:
            raise TypeError(f"unsupported action space: {sp!r}")

    # -- distribution params -------------------------------------------------

    def _gauss_std(self, log_std: jax.Array) -> jax.Array:
        return jax.nn.sigmoid(log_std / self.std_x_coef) * self.std_y_coef

    def _mixed_std(self) -> jax.Array:
        # Mixed tail uses plain sigmoid(log_std) * 0.5 (act.py:97,183).
        return jax.nn.sigmoid(self.log_std) * 0.5

    def _role_split(self, available_actions, x):
        """MixedRole: peel the role flag off the augmented availability mask
        (None — e.g. shape-only init — means all-discrete, unmasked)."""
        if available_actions is None:
            return jnp.zeros((*x.shape[:-1], 1)), None
        return available_actions[..., -1:], available_actions[..., : self.space.n]

    # -- sample --------------------------------------------------------------

    def sample(
        self,
        x: jax.Array,
        key: jax.Array,
        available_actions: Optional[jax.Array] = None,
        deterministic: bool = False,
    ) -> Tuple[jax.Array, jax.Array]:
        """-> (action (B, sample_dim) float, log_prob) per reference layout."""
        sp = self.space
        if isinstance(sp, Discrete) or (
            isinstance(sp, DCMLActionSpace) and not sp.mixed and not sp.extra
        ):
            logits = D.mask_logits(self.action_head(x), available_actions)
            a = D.categorical_mode(logits) if deterministic else D.categorical_sample(key, logits)
            logp = D.categorical_log_prob(logits, a)
            return a[..., None].astype(jnp.float32), logp[..., None]

        if isinstance(sp, Box) or (isinstance(sp, DCMLActionSpace) and sp.extra):
            mean = self.mean_head(x)
            std = self._gauss_std(self.log_std)
            a = mean if deterministic else D.normal_sample(key, mean, jnp.broadcast_to(std, mean.shape))
            logp = D.normal_log_prob(mean, std, a)
            return a, logp

        if isinstance(sp, MixedRole):
            role, avail = self._role_split(available_actions, x)
            logits = D.mask_logits(self.action_head(x), avail)
            k_disc, k_cont = jax.random.split(key)
            a_disc = D.categorical_mode(logits) if deterministic else D.categorical_sample(k_disc, logits)
            logp_disc = D.categorical_log_prob(logits, a_disc)[..., None]
            mean = self.mean_head(x)
            std = self._gauss_std(self.log_std)
            a_cont = mean if deterministic else D.normal_sample(key=k_cont, mean=mean, std=jnp.broadcast_to(std, mean.shape))
            logp_cont = D.normal_log_prob(mean, std, a_cont).sum(-1, keepdims=True)
            action = jnp.where(role > 0.5, a_cont, a_disc[..., None].astype(jnp.float32))
            return action, jnp.where(role > 0.5, logp_cont, logp_disc)

        if isinstance(sp, MultiDiscrete):
            # availability mask is the flat concat of per-head segments
            # (widths nvec[i]), matching the 2-D (agents, features) TimeStep
            # protocol; heads may have unequal widths (MPE move+comm)
            actions, logps = [], []
            keys = jax.random.split(key, len(sp.nvec))
            off = 0
            for i, head in enumerate(self.action_heads):
                n = sp.nvec[i]
                avail = None if available_actions is None else available_actions[..., off:off + n]
                off += n
                logits = D.mask_logits(head(x), avail)
                a = D.categorical_mode(logits) if deterministic else D.categorical_sample(keys[i], logits)
                actions.append(a[..., None].astype(jnp.float32))
                logps.append(D.categorical_log_prob(logits, a)[..., None])
            return jnp.concatenate(actions, -1), jnp.concatenate(logps, -1)

        if isinstance(sp, MultiBinary):
            logits = self.action_head(x)
            p = jax.nn.sigmoid(logits)
            if deterministic:
                a = (p > 0.5).astype(jnp.float32)
            else:
                a = jax.random.bernoulli(key, p).astype(jnp.float32)
            logp = (a * jax.nn.log_sigmoid(logits) + (1 - a) * jax.nn.log_sigmoid(-logits)).sum(
                -1, keepdims=True
            )
            return a, logp

        # DCML mixed: slice n_sub categorical groups + Gaussian tail
        # (act.py:83-105).
        assert isinstance(sp, DCMLActionSpace) and sp.mixed
        disc_logits = x[..., : sp.n_sub * sp.n].reshape(*x.shape[:-1], sp.n_sub, sp.n)
        if available_actions is not None:
            disc_logits = D.mask_logits(disc_logits, available_actions[..., : sp.n_sub, :])
        k_disc, k_cont = jax.random.split(key)
        if deterministic:
            a_disc = D.categorical_mode(disc_logits)
        else:
            a_disc = D.categorical_sample(k_disc, disc_logits)
        logp_disc = D.categorical_log_prob(disc_logits, a_disc)       # (B, n_sub)
        mean = x[..., sp.n_sub * sp.n :]
        std = self._mixed_std()
        a_cont = mean if deterministic else D.normal_sample(k_cont, mean, jnp.broadcast_to(std, mean.shape))
        logp_cont = D.normal_log_prob(mean, std, a_cont)              # (B, cont)
        action = jnp.concatenate([a_disc.astype(jnp.float32), a_cont], -1)
        logp = jnp.concatenate([logp_disc, logp_cont], -1).sum(-1, keepdims=True)
        return action, logp

    # -- evaluate ------------------------------------------------------------

    def evaluate(
        self,
        x: jax.Array,
        action: jax.Array,
        available_actions: Optional[jax.Array] = None,
        active_masks: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        """-> (log_prob, scalar entropy) matching ``act.py:144-226``."""
        sp = self.space
        if isinstance(sp, Discrete) or (
            isinstance(sp, DCMLActionSpace) and not sp.mixed and not sp.extra
        ):
            logits = D.mask_logits(self.action_head(x), available_actions)
            logp = D.categorical_log_prob(logits, action[..., 0])[..., None]
            ent = _masked_mean(D.categorical_entropy(logits), active_masks)
            return logp, ent

        if isinstance(sp, Box) or (isinstance(sp, DCMLActionSpace) and sp.extra):
            mean = self.mean_head(x)
            std = self._gauss_std(self.log_std)
            logp = D.normal_log_prob(mean, std, action)
            ent = _masked_mean(
                jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape), active_masks
            )
            return logp, ent

        if isinstance(sp, MixedRole):
            role, avail = self._role_split(available_actions, x)
            logits = D.mask_logits(self.action_head(x), avail)
            # Worker rows read the action as a categorical index; the master
            # row's float ratio truncates to a valid (discarded) index.
            logp_disc = D.categorical_log_prob(logits, action[..., 0].astype(jnp.int32))[..., None]
            mean = self.mean_head(x)
            std = self._gauss_std(self.log_std)
            logp_cont = D.normal_log_prob(mean, std, action).sum(-1, keepdims=True)
            logp = jnp.where(role > 0.5, logp_cont, logp_disc)
            ent_cont = jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape).sum(-1)
            ent_row = jnp.where(role[..., 0] > 0.5, ent_cont, D.categorical_entropy(logits))
            return logp, _masked_mean(ent_row, active_masks)

        if isinstance(sp, MultiDiscrete):
            logps, ents = [], []
            off = 0
            for i, head in enumerate(self.action_heads):
                n = sp.nvec[i]
                avail = None if available_actions is None else available_actions[..., off:off + n]
                off += n
                logits = D.mask_logits(head(x), avail)
                logps.append(D.categorical_log_prob(logits, action[..., i].astype(jnp.int32))[..., None])
                ents.append(_masked_mean(D.categorical_entropy(logits), active_masks))
            return jnp.concatenate(logps, -1), jnp.stack(ents).mean()

        if isinstance(sp, MultiBinary):
            logits = self.action_head(x)
            logp = (
                action * jax.nn.log_sigmoid(logits) + (1 - action) * jax.nn.log_sigmoid(-logits)
            ).sum(-1, keepdims=True)
            p = jax.nn.sigmoid(logits)
            ent_bits = -(p * jax.nn.log_sigmoid(logits) + (1 - p) * jax.nn.log_sigmoid(-logits))
            return logp, _masked_mean(ent_bits.sum(-1), active_masks)

        assert isinstance(sp, DCMLActionSpace) and sp.mixed
        a_disc = action[..., : sp.n_sub].astype(jnp.int32)
        a_cont = action[..., sp.n_sub :]
        disc_logits = x[..., : sp.n_sub * sp.n].reshape(*x.shape[:-1], sp.n_sub, sp.n)
        if available_actions is not None:
            disc_logits = D.mask_logits(disc_logits, available_actions[..., : sp.n_sub, :])
        logp_disc = D.categorical_log_prob(disc_logits, a_disc)        # (B, n_sub)
        ent_disc = _masked_mean(D.categorical_entropy(disc_logits).mean(-1), active_masks)
        mean = x[..., sp.n_sub * sp.n :]
        std = self._mixed_std()
        logp_cont = D.normal_log_prob(mean, std, a_cont)
        ent_cont = _masked_mean(
            jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape), active_masks
        )
        logp = jnp.concatenate([logp_disc, logp_cont], -1).sum(-1, keepdims=True)
        # act.py:195 — both parts divided by 0.98 before summing.
        entropy = ent_disc / 0.98 + ent_cont / 0.98
        return logp, entropy
