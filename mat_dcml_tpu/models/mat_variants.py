"""MAT ablation models: encoder-only, decoder-only, GRU.

References: ``mat_encoder.py`` (value + action heads off one unmasked trunk,
simultaneous decisions), ``mat_decoder.py`` (decoder-only; cross-attends raw
obs embeddings; value head inside the decoder), ``mat_gru.py`` (attention
blocks replaced by 2-layer GRUs over the agent axis).

Selected by ``--algorithm_name mat_encoder | mat_decoder | mat_gru``
(``transformer_policy.py:66-79``).  Like upstream, these support the
``discrete`` and ``continuous`` action families.

TPU notes: the encoder ablation needs no decode loop at all (one fused pass);
the decoder ablation reuses the KV-cache scan; the GRU ablation's
autoregressive decode carries GRU hidden state — the recurrent analogue of a
KV cache, one cell step per agent instead of the reference's full-sequence
re-run per agent (``mat_gru.py:167-169``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mat_dcml_tpu.models.mat import (
    CONTINUOUS,
    DISCRETE,
    MATConfig,
    NORMAL_STD,
    Head,
    ObsEncoder,
)
from mat_dcml_tpu.models.modules import gelu, DecodeBlock, EncodeBlock, dense, GAIN_ACT, init_decode_cache
from mat_dcml_tpu.ops import distributions as D


class VariantOutput(NamedTuple):
    value: jax.Array
    action: jax.Array
    log_prob: jax.Array


# ---------------------------------------------------------------------------
# MAT-Encoder: one trunk, simultaneous decisions (mat_encoder.py:87-137)
# ---------------------------------------------------------------------------

class MultiAgentEncoderModel(nn.Module):
    cfg: MATConfig

    def setup(self):
        c = self.cfg
        self.state_encoder = ObsEncoder(c.n_embd)
        self.obs_encoder = ObsEncoder(c.n_embd)
        self.ln = nn.LayerNorm()
        self.blocks = [EncodeBlock(c.n_embd, c.n_head) for _ in range(c.n_block)]
        self.head = Head(c.n_embd, c.n_objective)
        self.act_head = Head(c.n_embd, c.action_dim)
        if c.action_type != DISCRETE:
            self.log_std = self.param("log_std", lambda k: jnp.ones((c.action_dim,)))

    def __call__(self, state: jax.Array, obs: jax.Array):
        x = self.state_encoder(state) if self.cfg.encode_state else self.obs_encoder(obs)
        rep = self.ln(x)
        for blk in self.blocks:
            rep = blk(rep)
        return self.head(rep), rep, self.act_head(rep)

    def action_std(self):
        return jax.nn.sigmoid(self.log_std) * NORMAL_STD


class EncoderPolicy:
    """Simultaneous per-agent decisions (``mat_encoder.py:200-227``)."""

    def __init__(self, cfg: MATConfig):
        assert cfg.action_type in (DISCRETE, CONTINUOUS)
        self.cfg = cfg
        self.model = MultiAgentEncoderModel(cfg)
        self.act_out_dim = 1 if cfg.action_type == DISCRETE else cfg.action_dim
        self.act_prob_dim = self.act_out_dim

    def init_params(self, key):
        c = self.cfg
        return self.model.init(
            key,
            jnp.zeros((1, c.n_agent, c.state_dim)),
            jnp.zeros((1, c.n_agent, c.obs_dim)),
        )

    def get_actions(self, params, key, state, obs, available_actions=None, deterministic=False):
        v, _, logit = self.model.apply(params, state, obs)
        if self.cfg.action_type == DISCRETE:
            logit = D.mask_logits(logit, available_actions)
            idx = D.categorical_mode(logit) if deterministic else D.categorical_sample(key, logit)
            logp = D.categorical_log_prob(logit, idx)
            return VariantOutput(v, idx[..., None].astype(jnp.float32), logp[..., None])
        std = self.model.apply(params, method="action_std")
        act = logit if deterministic else D.normal_sample(key, logit, std)
        logp = D.normal_log_prob(logit, std, act)
        return VariantOutput(v, act, logp)

    def evaluate_actions(self, params, state, obs, action, available_actions=None):
        v, _, logit = self.model.apply(params, state, obs)
        if self.cfg.action_type == DISCRETE:
            logit = D.mask_logits(logit, available_actions)
            idx = action[..., 0].astype(jnp.int32)
            logp = D.categorical_log_prob(logit, idx)[..., None]
            ent = D.categorical_entropy(logit)[..., None]
        else:
            std = self.model.apply(params, method="action_std")
            logp = D.normal_log_prob(logit, std, action)
            ent = jnp.broadcast_to(D.normal_entropy(logit, std), logit.shape)
        return v, logp, ent

    def get_values(self, params, state, obs):
        v, _, _ = self.model.apply(params, state, obs)
        return v


# ---------------------------------------------------------------------------
# MAT-Decoder: decoder-only with internal value head (mat_decoder.py:170-218)
# ---------------------------------------------------------------------------

class MultiAgentDecoderModel(nn.Module):
    cfg: MATConfig

    def setup(self):
        c = self.cfg
        if c.action_type == DISCRETE:
            self.action_encoder_nobias = dense(c.n_embd, gain=GAIN_ACT, use_bias=False)
        else:
            self.log_std = self.param("log_std", lambda k: jnp.ones((c.action_dim,)))
            self.action_encoder_bias = dense(c.n_embd, gain=GAIN_ACT)
        self.obs_encoder = ObsEncoder(c.n_embd)
        self.ln = nn.LayerNorm()
        self.blocks = [DecodeBlock(c.n_embd, c.n_head) for _ in range(c.n_block)]
        self.head = Head(c.n_embd, c.action_dim)
        self.val_head = Head(c.n_embd, c.n_objective)

    def _embed_action(self, a):
        enc = self.action_encoder_nobias if self.cfg.action_type == DISCRETE else self.action_encoder_bias
        return gelu(enc(a))

    def __call__(self, shifted_action: jax.Array, obs: jax.Array):
        """Full pass -> (logits, values); cross-attention keys on obs
        embeddings directly (``mat_decoder.py:206-218``)."""
        obs_emb = self.obs_encoder(obs)
        x = self.ln(self._embed_action(shifted_action))
        for blk in self.blocks:
            x = blk(x, obs_emb)
        return self.head(x), self.val_head(x)

    def decode_step(self, shifted_i, obs_i, caches, i):
        obs_emb_i = self.obs_encoder(obs_i)
        x = self.ln(self._embed_action(shifted_i))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk.decode_step(x, obs_emb_i, cache, i)
            new_caches.append(cache)
        return self.head(x), self.val_head(x), new_caches

    def action_std(self):
        return jax.nn.sigmoid(self.log_std) * NORMAL_STD

    def fresh_cache(self, batch, dtype=jnp.float32):
        return init_decode_cache(self.cfg.n_block, batch, self.cfg.n_agent, self.cfg.n_embd, dtype)


class DecoderPolicy:
    """AR decode carrying per-position value (``mat_decoder.py:16-37``).

    The reference's ``get_values`` runs a (stochastic) decode and returns its
    values (``mat_decoder.py:291-294``); hence ``get_values`` takes a key.
    """

    def __init__(self, cfg: MATConfig):
        assert cfg.action_type in (DISCRETE, CONTINUOUS)
        self.cfg = cfg
        self.model = MultiAgentDecoderModel(cfg)
        self.act_out_dim = 1 if cfg.action_type == DISCRETE else cfg.action_dim
        self.act_prob_dim = self.act_out_dim

    def init_params(self, key):
        c = self.cfg
        return self.model.init(
            key,
            jnp.zeros((1, c.n_agent, c.action_input_dim)),
            jnp.zeros((1, c.n_agent, c.obs_dim)),
        )

    def get_actions(self, params, key, state, obs, available_actions=None, deterministic=False):
        del state  # decoder-only: conditions on obs alone
        c = self.cfg
        B, A, adim = obs.shape[0], c.n_agent, c.action_dim
        in_dim = c.action_input_dim
        if available_actions is None:
            available_actions = jnp.ones((B, A, adim), jnp.float32)
        std = self.model.apply(params, method="action_std") if c.action_type != DISCRETE else None

        start = jnp.zeros((B, 1, in_dim), jnp.float32)
        if c.action_type == DISCRETE:
            start = start.at[:, 0, 0].set(1.0)
        caches = self.model.apply(params, B, method="fresh_cache")

        def body(carry, i):
            caches, shifted_in, key = carry
            key, k = jax.random.split(key)
            obs_i = jax.lax.dynamic_slice_in_dim(obs, i, 1, axis=1)
            logits, val, caches = self.model.apply(
                params, shifted_in, obs_i, caches, i, method="decode_step"
            )
            logits = logits[:, 0]
            if c.action_type == DISCRETE:
                ava_i = jax.lax.dynamic_slice_in_dim(available_actions, i, 1, axis=1)[:, 0]
                masked = D.mask_logits(logits, ava_i)
                idx = D.categorical_mode(masked) if deterministic else D.categorical_sample(k, masked)
                logp = D.categorical_log_prob(masked, idx)
                act = idx[:, None].astype(jnp.float32)
                logp = logp[:, None]
                nxt = jnp.zeros((B, 1, in_dim)).at[:, 0, 1:].set(jax.nn.one_hot(idx, adim))
            else:
                act = logits if deterministic else D.normal_sample(k, logits, std)
                logp = D.normal_log_prob(logits, std, act)
                nxt = act[:, None, :]
            return (caches, nxt, key), (act, logp, val[:, 0])

        _, (acts, logps, vals) = jax.lax.scan(body, (caches, start, key), jnp.arange(A))
        return VariantOutput(
            jnp.swapaxes(vals, 0, 1), jnp.swapaxes(acts, 0, 1), jnp.swapaxes(logps, 0, 1)
        )

    def evaluate_actions(self, params, state, obs, action, available_actions=None):
        del state
        c = self.cfg
        B, A, adim = obs.shape[0], c.n_agent, c.action_dim
        if c.action_type == DISCRETE:
            idx = action[..., 0].astype(jnp.int32)
            onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
            shifted = jnp.zeros((B, A, adim + 1)).at[:, 0, 0].set(1.0).at[:, 1:, 1:].set(onehot[:, :-1])
            logits, vals = self.model.apply(params, shifted, obs)
            logits = D.mask_logits(logits, available_actions)
            logp = D.categorical_log_prob(logits, idx)[..., None]
            ent = D.categorical_entropy(logits)[..., None]
        else:
            shifted = jnp.zeros((B, A, adim)).at[:, 1:].set(action[:, :-1])
            mean, vals = self.model.apply(params, shifted, obs)
            std = self.model.apply(params, method="action_std")
            logp = D.normal_log_prob(mean, std, action)
            ent = jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape)
        return vals, logp, ent

    def get_values(self, params, state, obs, key=None, available_actions=None):
        key = key if key is not None else jax.random.key(0)
        return self.get_actions(params, key, state, obs, available_actions).value


# ---------------------------------------------------------------------------
# MAT-GRU: recurrence over the agent axis (mat_gru.py)
# ---------------------------------------------------------------------------

class StackedGRU(nn.Module):
    """2-layer GRU over the agent axis (torch ``nn.GRU(num_layers=2)``)."""

    n_embd: int
    n_layers: int = 2

    def setup(self):
        self.cells = [nn.GRUCell(features=self.n_embd) for _ in range(self.n_layers)]

    def __call__(self, x: jax.Array):
        """Full sequence: ``(B, L, D) -> (B, L, D)``.  The agent axis is short
        and static, so a Python loop (unrolled by XLA) is simplest here; the
        autoregressive path uses :meth:`step` with an explicit carry."""
        carry = self.initial_carry(x.shape[0])
        ys = []
        for t in range(x.shape[1]):
            carry, y = self.step(carry, x[:, t])
            ys.append(y)
        return jnp.stack(ys, axis=1)

    def step(self, carry, x_t):
        new_carry = []
        h = x_t
        for cell, c in zip(self.cells, carry):
            c2, h = cell(c, h)
            new_carry.append(c2)
        return new_carry, h

    def initial_carry(self, batch: int):
        return [jnp.zeros((batch, self.n_embd)) for _ in range(self.n_layers)]


class MultiAgentGRUModel(nn.Module):
    """Encoder/decoder with GRUs in place of attention (``mat_gru.py:20-98``)."""

    cfg: MATConfig

    def setup(self):
        c = self.cfg
        self.obs_encoder = ObsEncoder(c.n_embd)
        self.enc_ln = nn.LayerNorm()
        self.enc_gru = StackedGRU(c.n_embd)
        self.head = Head(c.n_embd, c.n_objective)

        if c.action_type == DISCRETE:
            self.action_encoder_nobias = dense(c.n_embd, gain=GAIN_ACT, use_bias=False)
        else:
            self.log_std = self.param("log_std", lambda k: jnp.ones((c.action_dim,)))
            self.action_encoder_bias = dense(c.n_embd, gain=GAIN_ACT)
        self.dec_ln = nn.LayerNorm()
        self.dec_gru = StackedGRU(c.n_embd)
        self.act_head = Head(c.n_embd, c.action_dim)

    def encode(self, state, obs):
        del state  # mat_gru.py:45-48: obs only
        rep = self.enc_gru(self.enc_ln(self.obs_encoder(obs)))
        return self.head(rep), rep

    def _embed_action(self, a):
        enc = self.action_encoder_nobias if self.cfg.action_type == DISCRETE else self.action_encoder_bias
        return gelu(enc(a))

    def decode_full(self, shifted_action, obs_rep, obs):
        del obs
        x = self._embed_action(shifted_action) + obs_rep  # mat_gru.py:92-94
        x = self.dec_gru(self.dec_ln(x))
        return self.act_head(x)

    def decode_step(self, shifted_i, rep_i, carry, i):
        del i
        x = self._embed_action(shifted_i) + rep_i          # (B, 1, D)
        x = self.dec_ln(x)[:, 0]
        carry, h = self.dec_gru.step(carry, x)
        return self.act_head(h)[:, None, :], carry

    def initial_decode_carry(self, batch: int):
        return self.dec_gru.initial_carry(batch)

    def action_std(self):
        return jax.nn.sigmoid(self.log_std) * NORMAL_STD


class GRUPolicy:
    """Same act API as MAT; hidden-state carry instead of KV caches."""

    def __init__(self, cfg: MATConfig):
        assert cfg.action_type in (DISCRETE, CONTINUOUS)
        self.cfg = cfg
        self.model = MultiAgentGRUModel(cfg)
        self.act_out_dim = 1 if cfg.action_type == DISCRETE else cfg.action_dim
        self.act_prob_dim = self.act_out_dim

    def init_params(self, key):
        c = self.cfg

        def init_fn(mdl, state, obs, shifted):
            v, rep = mdl.encode(state, obs)
            logit = mdl.decode_full(shifted, rep, obs)
            return v, logit

        return self.model.init(
            key,
            jnp.zeros((1, c.n_agent, c.state_dim)),
            jnp.zeros((1, c.n_agent, c.obs_dim)),
            jnp.zeros((1, c.n_agent, c.action_input_dim)),
            method=init_fn,
        )

    def get_actions(self, params, key, state, obs, available_actions=None, deterministic=False):
        c = self.cfg
        B, A, adim = obs.shape[0], c.n_agent, c.action_dim
        in_dim = c.action_input_dim
        v, rep = self.model.apply(params, state, obs, method="encode")
        if available_actions is None:
            available_actions = jnp.ones((B, A, adim), jnp.float32)
        std = self.model.apply(params, method="action_std") if c.action_type != DISCRETE else None

        start = jnp.zeros((B, 1, in_dim), jnp.float32)
        if c.action_type == DISCRETE:
            start = start.at[:, 0, 0].set(1.0)
        carry0 = [jnp.zeros((B, c.n_embd)) for _ in range(2)]

        def body(carry, i):
            gru_carry, shifted_in, key = carry
            key, k = jax.random.split(key)
            rep_i = jax.lax.dynamic_slice_in_dim(rep, i, 1, axis=1)
            logits, gru_carry = self.model.apply(
                params, shifted_in, rep_i, gru_carry, i, method="decode_step"
            )
            logits = logits[:, 0]
            if c.action_type == DISCRETE:
                ava_i = jax.lax.dynamic_slice_in_dim(available_actions, i, 1, axis=1)[:, 0]
                masked = D.mask_logits(logits, ava_i)
                idx = D.categorical_mode(masked) if deterministic else D.categorical_sample(k, masked)
                logp = D.categorical_log_prob(masked, idx)
                act = idx[:, None].astype(jnp.float32)
                logp = logp[:, None]
                nxt = jnp.zeros((B, 1, in_dim)).at[:, 0, 1:].set(jax.nn.one_hot(idx, adim))
            else:
                act = logits if deterministic else D.normal_sample(k, logits, std)
                logp = D.normal_log_prob(logits, std, act)
                nxt = act[:, None, :]
            return (gru_carry, nxt, key), (act, logp)

        _, (acts, logps) = jax.lax.scan(body, (carry0, start, key), jnp.arange(A))
        return VariantOutput(v, jnp.swapaxes(acts, 0, 1), jnp.swapaxes(logps, 0, 1))

    def evaluate_actions(self, params, state, obs, action, available_actions=None):
        c = self.cfg
        B, A, adim = obs.shape[0], c.n_agent, c.action_dim
        v, rep = self.model.apply(params, state, obs, method="encode")
        if c.action_type == DISCRETE:
            idx = action[..., 0].astype(jnp.int32)
            onehot = jax.nn.one_hot(idx, adim, dtype=jnp.float32)
            shifted = jnp.zeros((B, A, adim + 1)).at[:, 0, 0].set(1.0).at[:, 1:, 1:].set(onehot[:, :-1])
            logits = self.model.apply(params, shifted, rep, obs, method="decode_full")
            logits = D.mask_logits(logits, available_actions)
            logp = D.categorical_log_prob(logits, idx)[..., None]
            ent = D.categorical_entropy(logits)[..., None]
        else:
            shifted = jnp.zeros((B, A, adim)).at[:, 1:].set(action[:, :-1])
            mean = self.model.apply(params, shifted, rep, obs, method="decode_full")
            std = self.model.apply(params, method="action_std")
            logp = D.normal_log_prob(mean, std, action)
            ent = jnp.broadcast_to(D.normal_entropy(mean, std), mean.shape)
        return v, logp, ent

    def get_values(self, params, state, obs):
        v, _ = self.model.apply(params, state, obs, method="encode")
        return v
