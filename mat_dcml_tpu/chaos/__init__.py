"""Deterministic, seeded fault injection for the serving/training stack.

``chaos/plan.py`` declares *what* goes wrong and when (a JSON-loadable
:class:`FaultPlan` of typed fault events, seed-reproducibly expanded into a
concrete schedule); ``chaos/inject.py`` is *how* — a :class:`FaultInjector`
armed at existing seams (engine decode, batcher dequeue, checkpoint IO,
trajectory queue, param publisher, dispatch launch).  Disarmed seams are a
single ``is None`` check, so production paths pay nothing.
``chaos/invariants.py`` turns a soak's metrics stream into pass/fail
contracts, and ``scripts/chaos_soak.py`` drives the whole thing.
"""

from mat_dcml_tpu.chaos.inject import (
    ActorThreadDeath,
    FaultInjector,
    InjectedFault,
    InjectedIOError,
    arm,
    disarm,
    is_silent_death,
)
from mat_dcml_tpu.chaos.invariants import InvariantResult, check_invariants
from mat_dcml_tpu.chaos.plan import FAULT_KINDS, FaultEvent, FaultPlan

__all__ = [
    "FAULT_KINDS",
    "ActorThreadDeath",
    "FaultEvent",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "InjectedIOError",
    "InvariantResult",
    "arm",
    "check_invariants",
    "disarm",
    "is_silent_death",
]
