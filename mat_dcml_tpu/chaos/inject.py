"""Fault injector: arms a :class:`FaultPlan` at the stack's existing seams.

Seam sites (engine decode, batcher dequeue, checkpoint IO, trajectory queue
put/get, param publish, dispatch launch, anomaly signals) all follow one
pattern::

    from mat_dcml_tpu.chaos import inject as _chaos
    ...
    if _chaos.ACTIVE is not None:
        _chaos.ACTIVE.on_decode(replica_id)

Disarmed (the production default) that is a module-attribute read and an
``is None`` branch — no allocation, no lock, no call.  Armed, each hook
checks the plan under a lock and either returns, sleeps (latency faults), or
raises a typed :class:`InjectedFault` (crash faults).  Every fired event
emits a ``{"chaos": "fired", ...}`` record through ``record_sink`` plus
``chaos_*`` counters through telemetry, and :meth:`suppression_for` lets the
anomaly paths correlate trips with the injected fault that explains them —
expected faults are suppressed (counted + recorded) instead of paging.

The injector is process-local: the soak driver arms serving-plane events in
its own process and each trainer subprocess arms its own plane's sub-plan.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from mat_dcml_tpu.chaos.plan import FaultEvent, FaultPlan

# The armed injector, or None.  Seam sites read this attribute directly.
ACTIVE: Optional["FaultInjector"] = None


class InjectedFault(RuntimeError):
    """A fault raised on purpose by the chaos injector."""

    def __init__(self, msg: str, event_id: str = ""):
        super().__init__(msg)
        self.event_id = event_id


class InjectedIOError(InjectedFault, OSError):
    """Injected transient IO failure — an ``OSError`` so retry paths treat it
    exactly like a real filesystem hiccup."""


class ActorThreadDeath(InjectedFault):
    """Kills the actor thread *silently*: ``ActorWorker.run`` recognizes it
    via :func:`is_silent_death` and returns without recording an error or
    closing the queue — reproducing the pathological dead-thread mode the
    learner liveness check exists for."""


def is_silent_death(exc: BaseException) -> bool:
    return isinstance(exc, ActorThreadDeath)


# Which anomaly kinds an injected fault is *expected* to trip (prefix match).
# A trip whose kind matches an active/just-cleared event's entry here is
# suppressed: counted + recorded, but no flight-recorder bundle, no profiler
# trigger, no page.
_SUPPRESSES: Dict[str, tuple] = {
    "replica_crash": ("slo_",),
    "replica_hang": ("slo_",),
    "decode_error": ("slo_",),
    "queue_stall": ("slo_",),
    "load_spike": ("slo_",),
    "checkpoint_io_error": ("step_time",),
    "checkpoint_corrupt": ("step_time",),
    "nan_grad": ("nonfinite",),
    "actor_thread_death": ("step_time", "staleness"),
    "actor_crash": ("step_time", "staleness"),
    "param_publish_delay": ("staleness", "step_time"),
    "trainer_kill": (),
    # a killed host tanks service latency until its siblings absorb the
    # load and the prober readmits nothing (the host stays dead)
    "host_loss": ("slo_",),
}

# Kinds gated by call count (fire on the Nth matching hook call) rather than
# by wall-clock window alone — training timing is compile-dominated, so call
# counts are the deterministic clock there.
_COUNT_GATED = frozenset({
    "decode_error", "checkpoint_io_error", "checkpoint_corrupt",
    "nan_grad", "actor_thread_death", "actor_crash", "host_loss",
})


class _EventState:
    __slots__ = ("event", "fired", "cleared", "skips_left", "budget_left",
                 "last_fire_s")

    def __init__(self, event: FaultEvent):
        self.event = event
        self.fired = False
        self.cleared = False
        self.skips_left = int(event.params.get("skip_calls", 0))
        self.budget_left = (int(event.params.get("fail_calls", 1))
                            if event.kind in _COUNT_GATED else None)
        self.last_fire_s = -1.0


def jsonl_sink(path: str | Path) -> Callable[[dict], None]:
    """Append-per-record jsonl sink (opens with ``'a'`` per write so it can
    share a file with :class:`MetricsWriter` safely on POSIX)."""
    path = Path(path)
    lock = threading.Lock()

    def sink(record: dict) -> None:
        with lock:
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")

    return sink


class FaultInjector:
    """Executes an expanded :class:`FaultPlan` against the seam hooks.

    ``time_fn`` is injectable for tests; the schedule clock starts at
    :meth:`start` (call it after warmup so ``at_s`` means "seconds into the
    steady run").  Hooks called before ``start`` are no-ops.
    """

    def __init__(self, plan: FaultPlan, telemetry=None,
                 record_sink: Optional[Callable[[dict], None]] = None,
                 time_fn: Callable[[], float] = time.monotonic,
                 suppression_grace_s: float = 30.0,
                 log=print):
        self.plan = plan.expand()
        self.telemetry = telemetry
        self.record_sink = record_sink
        self.time_fn = time_fn
        self.suppression_grace_s = float(suppression_grace_s)
        self.log = log
        self._t0: Optional[float] = None
        self._lock = threading.Lock()
        self._states = [_EventState(ev) for ev in self.plan.events]
        self._records: List[dict] = []

    # ---------------------------------------------------------------- admin

    def start(self) -> None:
        """Start the schedule clock (idempotent)."""
        with self._lock:
            if self._t0 is None:
                self._t0 = self.time_fn()

    def now(self) -> Optional[float]:
        return None if self._t0 is None else self.time_fn() - self._t0

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def fired_sequence(self) -> List[str]:
        """Event ids in firing order — the reproducibility artifact's view of
        what actually happened (vs. the schedule's view of what should)."""
        return [r["event_id"] for r in self.records()
                if r.get("chaos") == "fired"]

    def poll(self) -> None:
        """Emit ``cleared`` records for fired events whose window has passed
        (the soak driver calls this periodically)."""
        t = self.now()
        if t is None:
            return
        with self._lock:
            for st in self._states:
                if (st.fired and not st.cleared
                        and not self._active_locked(st, t)
                        and t >= st.event.end_s):
                    self._clear_locked(st, t)

    def finish(self) -> None:
        """Clear everything still open and drop the active gauge."""
        t = self.now()
        with self._lock:
            if t is not None:
                for st in self._states:
                    if st.fired and not st.cleared:
                        self._clear_locked(st, t)
        self._gauge("chaos_active", 0.0)

    # ------------------------------------------------------------ internals

    def _count(self, name: str, n: int = 1) -> None:
        if self.telemetry is not None:
            self.telemetry.count(name, n)

    def _gauge(self, name: str, value: float) -> None:
        if self.telemetry is not None:
            self.telemetry.gauge(name, value)

    def _emit_locked(self, record: dict) -> None:
        self._records.append(record)
        if self.record_sink is not None:
            self.record_sink(record)

    def _active_locked(self, st: _EventState, t: float) -> bool:
        """Is the event's schedule window open at plan-time ``t``?"""
        ev = st.event
        if t < ev.at_s:
            return False
        if ev.kind in _COUNT_GATED:
            return st.budget_left is None or st.budget_left > 0
        return ev.duration_s <= 0 or t < ev.end_s

    def _clear_locked(self, st: _EventState, t: float) -> None:
        st.cleared = True
        self._emit_locked({
            "chaos": "cleared", "event_id": st.event.event_id,
            "kind": st.event.kind, "t_s": round(t, 3),
            "duration_s": float(st.event.duration_s),
        })
        self.log(f"[chaos] cleared {st.event.event_id} at t={t:.2f}s")

    def _matches_target(self, ev: FaultEvent, target: Optional[str]) -> bool:
        if ev.target is None:
            return True
        return str(ev.target) == str(target)

    def _fire(self, st: _EventState, t: float) -> str:
        """Record one injected occurrence; returns the event id.  Caller
        holds the lock."""
        ev = st.event
        if st.budget_left is not None:
            st.budget_left -= 1
        st.last_fire_s = t
        self._count("chaos_injected_faults")
        if not st.fired:
            st.fired = True
            self._count("chaos_events_fired")
            rec = {"chaos": "fired", "event_id": ev.event_id,
                   "kind": ev.kind, "at_s": float(ev.at_s),
                   "t_s": round(t, 3)}
            if ev.target is not None:
                rec["target"] = str(ev.target)
            self._emit_locked(rec)
            self.log(f"[chaos] fired {ev.event_id} at t={t:.2f}s "
                     f"(target={ev.target})")
        return ev.event_id

    def _claim(self, kind: str, target: Optional[str] = None,
               call_index: Optional[int] = None):
        """Find a matching armed event and consume one firing from it.

        Returns ``(event, plan_time)`` or ``None``.  Sleeping/raising happens
        in the hook, outside the lock.
        """
        t = self.now()
        if t is None:
            return None
        with self._lock:
            for st in self._states:
                ev = st.event
                if ev.kind != kind or not self._matches_target(ev, target):
                    continue
                at_iter = ev.params.get("at_iteration")
                if at_iter is not None:
                    if call_index is None or call_index < int(at_iter):
                        continue
                elif not self._active_locked(st, t):
                    continue
                if at_iter is not None and not self._active_locked(st, t):
                    continue        # budget exhausted / before at_s
                if st.skips_left > 0:
                    st.skips_left -= 1
                    continue
                self._fire(st, t)
                return ev, t
        return None

    # ----------------------------------------------------------- seam hooks

    def on_decode(self, replica_id=None) -> None:
        """DecodeEngine.decode: crash, hang, or transient decode error."""
        rid = None if replica_id is None else f"r{replica_id}"
        hit = self._claim("replica_crash", rid)
        if hit is not None:
            raise InjectedFault(
                f"injected replica crash ({hit[0].event_id})",
                event_id=hit[0].event_id)
        hit = self._claim("decode_error", rid)
        if hit is not None:
            raise InjectedFault(
                f"injected decode error ({hit[0].event_id})",
                event_id=hit[0].event_id)
        hit = self._claim("replica_hang", rid)
        if hit is not None:
            time.sleep(float(hit[0].params.get("sleep_s", 0.25)))

    def on_dequeue(self) -> None:
        """ContinuousBatcher dispatch loop: stall before collecting a batch
        so the queue grows and shed/429 behavior is exercised honestly."""
        hit = self._claim("queue_stall", "batcher")
        if hit is None:
            hit = self._claim("queue_stall", None)
        if hit is not None:
            time.sleep(float(hit[0].params.get("sleep_s", 0.2)))

    def on_checkpoint_io(self, op: str) -> None:
        """CheckpointManager save/restore/flush IO attempts.  ``target``
        selects the op (``save``/``restore``/``flush``); None hits all."""
        hit = self._claim("checkpoint_io_error", op)
        if hit is not None:
            raise InjectedIOError(
                f"injected checkpoint {op} IO error ({hit[0].event_id})",
                event_id=hit[0].event_id)

    def on_checkpoint_saved(self, step_dir) -> None:
        """After a checkpoint's integrity manifest lands: corrupt the largest
        file so CRC verification (and quarantine fallback) is exercised."""
        hit = self._claim("checkpoint_corrupt")
        if hit is not None:
            corrupted = corrupt_step_dir(step_dir)
            self.log(f"[chaos] corrupted {corrupted} ({hit[0].event_id})")

    def on_queue_put(self) -> None:
        hit = self._claim("queue_stall", "trajectory")
        if hit is not None:
            time.sleep(float(hit[0].params.get("sleep_s", 0.1)))

    def on_queue_get(self) -> None:
        hit = self._claim("queue_stall", "trajectory_get")
        if hit is not None:
            time.sleep(float(hit[0].params.get("sleep_s", 0.1)))

    def on_param_publish(self) -> None:
        hit = self._claim("param_publish_delay")
        if hit is not None:
            time.sleep(float(hit[0].params.get("sleep_s", 0.1)))

    def on_dispatch(self) -> None:
        """base_runner dispatch launch: latency injection via queue_stall
        events targeted at ``dispatch``."""
        hit = self._claim("queue_stall", "dispatch")
        if hit is not None:
            time.sleep(float(hit[0].params.get("sleep_s", 0.1)))

    def on_actor_iteration(self, iteration: int,
                           worker: Optional[str] = None) -> None:
        """Top of the actor thread loop; ``params.at_iteration`` is the
        deterministic trigger.  ``worker`` is the calling worker's label
        (``"w<idx>"``) — ``actor_crash`` events match it against their
        ``target`` to kill one specific worker out of N, while the legacy
        ``actor_thread_death`` ignores it (any worker can satisfy it)."""
        hit = self._claim("actor_thread_death", call_index=iteration)
        if hit is not None:
            raise ActorThreadDeath(
                f"injected silent actor death ({hit[0].event_id})",
                event_id=hit[0].event_id)
        hit = self._claim("actor_crash", worker, call_index=iteration)
        if hit is not None:
            raise ActorThreadDeath(
                f"injected actor worker crash ({hit[0].event_id}, "
                f"worker={worker})",
                event_id=hit[0].event_id)

    def on_anomaly_signals(self, signals: Dict[str, float],
                           call_index: Optional[int] = None,
                           ) -> Dict[str, float]:
        """Mutate the anomaly-signal dict before the detector observes it —
        nan_grad injects the *signal*, never the training math, so the run
        stays bit-exact while the paging path is exercised end to end."""
        hit = self._claim("nan_grad", call_index=call_index)
        if hit is not None:
            signals = dict(signals)
            signals["nonfinite_grads"] = max(
                1.0, float(signals.get("nonfinite_grads", 0.0)))
        return signals

    def claim_host_loss(self, host: Optional[str] = None):
        """Driver-delivered fault (like ``trainer_kill``'s SIGTERM): the
        federation soak driver polls this per host (``target`` ``"h<idx>"``)
        and SIGKILLs the matching host subprocess when an armed ``host_loss``
        event's window opens.  Count-gated with a default budget of 1, so
        the kill fires exactly once.  Returns ``(event, plan_time)`` or
        ``None``."""
        return self._claim("host_loss", host)

    def load_multiplier(self) -> float:
        """Offered-load multiplier for the load generator (product of active
        load_spike factors; 1.0 when none)."""
        t = self.now()
        if t is None:
            return 1.0
        mult = 1.0
        with self._lock:
            for st in self._states:
                if (st.event.kind == "load_spike"
                        and self._active_locked(st, t)):
                    if not st.fired:        # one fired record per spike, not
                        self._fire(st, t)   # one per load-loop poll
                    st.last_fire_s = t
                    mult *= float(st.event.params.get("factor", 2.0))
        return mult

    # ---------------------------------------------------------- suppression

    def suppression_for(self, anomaly_kind: str) -> Optional[str]:
        """If an active (or recently-cleared, within the grace window) event
        is expected to trip this anomaly kind, consume the trip: bump the
        suppression counter, emit a ``suppressed`` record, and return the
        chaos event id.  Returns None when the anomaly is *not* explained by
        the plan and should page normally."""
        t = self.now()
        if t is None:
            return None
        with self._lock:
            for st in self._states:
                ev = st.event
                prefixes = _SUPPRESSES.get(ev.kind, ())
                if not any(anomaly_kind.startswith(p) for p in prefixes):
                    continue
                open_until = max(ev.end_s, st.last_fire_s) \
                    + self.suppression_grace_s
                if not (ev.at_s <= t <= open_until):
                    continue
                self._count("chaos_suppressed_anomalies")
                self._emit_locked({
                    "chaos": "suppressed", "event_id": ev.event_id,
                    "kind": ev.kind, "suppressed_kind": anomaly_kind,
                    "t_s": round(t, 3),
                })
                return ev.event_id
        return None


def corrupt_step_dir(step_dir) -> str:
    """Flip one byte in the middle of the largest file under ``step_dir`` —
    the canonical bit-rot injection the CRC manifests exist to catch."""
    step_dir = Path(step_dir)
    files = [p for p in step_dir.rglob("*") if p.is_file()
             and p.stat().st_size > 0]
    if not files:
        raise FileNotFoundError(f"nothing to corrupt under {step_dir}")
    victim = max(files, key=lambda p: p.stat().st_size)
    with open(victim, "r+b") as f:
        f.seek(victim.stat().st_size // 2)
        byte = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([byte[0] ^ 0xFF]))
    return str(victim)


def arm(injector: FaultInjector) -> FaultInjector:
    """Install ``injector`` as the process-wide active injector."""
    global ACTIVE
    ACTIVE = injector
    if injector.telemetry is not None:
        injector.telemetry.count("chaos_events_armed",
                                 len(injector.plan.events))
        injector.telemetry.gauge("chaos_active", 1.0)
    return injector


def disarm() -> None:
    global ACTIVE
    if ACTIVE is not None:
        ACTIVE.finish()
    ACTIVE = None
