"""Fault plans: a JSON-loadable, seed-reproducible schedule of fault events.

A plan is declarative — *what* goes wrong, *when*, and *where* — and carries
no injection machinery (that's :mod:`mat_dcml_tpu.chaos.inject`).  Schedule
fields may be randomized in the JSON (``at_s``/``duration_s`` as a
``[lo, hi]`` range, ``target`` as a list of choices); :meth:`FaultPlan.expand`
resolves them with ``random.Random(seed)`` into a concrete schedule, so the
expansion is a pure function of (plan JSON, seed) and re-running the same
pair reproduces the same injection sequence exactly.

Plan JSON::

    {
      "name": "smoke",
      "events": [
        {"kind": "replica_hang", "at_s": 2.0, "duration_s": 1.5,
         "target": "r0", "params": {"sleep_s": 0.05}},
        {"kind": "load_spike", "at_s": [4.0, 5.0], "duration_s": 3.0,
         "params": {"factor": 3.0}}
      ]
    }

Count-gated kinds (checkpoint_io_error, decode_error, checkpoint_corrupt,
actor_thread_death, actor_crash, nan_grad, host_loss) fire on the Nth hook
call inside their window
via ``params`` (``fail_calls``, ``skip_calls``, ``at_iteration``) rather than
wall-clock alone — training-plane timing is compile-dominated, so call counts
are the deterministic clock there.
"""

from __future__ import annotations

import dataclasses
import json
import random
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Every fault kind the injector understands, and the plane whose process arms
# it (the soak driver partitions a plan by plane — serving faults arm in the
# driver process, training faults in the trainer subprocess they target).
FAULT_KINDS: Dict[str, str] = {
    "replica_crash": "serving",        # decode raises for the whole window
    "replica_hang": "serving",         # decode sleeps (latency injection)
    "decode_error": "serving",         # N transient decode failures
    "queue_stall": "serving",          # batcher dispatch loop sleeps
    "load_spike": "serving",           # loadgen offered-QPS multiplier
    "checkpoint_io_error": "train_sync",   # save/restore raises transient IO
    "checkpoint_corrupt": "train_sync",    # byte-flip a finished checkpoint
    "nan_grad": "train_sync",          # nonfinite_grads anomaly signal
    "trainer_kill": "train_sync",      # orchestrator-level SIGTERM
    "actor_thread_death": "train_async",   # actor thread dies silently
    "param_publish_delay": "train_async",  # publisher sleeps per publish
    # a SPECIFIC actor worker (target "w<idx>") dies silently under load —
    # the N-worker generalization of actor_thread_death, exercising the
    # per-worker restart path + admission-ticket reclaim
    "actor_crash": "train_async",
    # a whole HOST fleet (target "h<idx>") dies under load: the soak driver
    # claims this and SIGKILLs the host subprocess; the service router must
    # fail the in-flight requests over to sibling hosts with zero drops
    "host_loss": "service",
}


def _resolve(value: Any, rng: random.Random) -> Any:
    """``[lo, hi]`` numeric pair -> uniform draw; list -> choice; else as-is."""
    if isinstance(value, (list, tuple)):
        if (len(value) == 2
                and all(isinstance(v, (int, float)) for v in value)):
            lo, hi = float(value[0]), float(value[1])
            return rng.uniform(lo, hi)
        return rng.choice(list(value))
    return value


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.  ``at_s``/``duration_s`` are seconds relative to
    injector start (post-warmup); ``duration_s == 0`` means the event has no
    window and is gated purely by its count params.  ``event_id`` is assigned
    at expansion (``<kind>:<index>``) and keys suppression + metrics."""

    kind: str
    at_s: float = 0.0
    duration_s: float = 0.0
    target: Optional[str] = None
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    event_id: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; "
                f"known: {sorted(FAULT_KINDS)}")

    @property
    def end_s(self) -> float:
        return float(self.at_s) + float(self.duration_s)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "at_s": self.at_s,
            "duration_s": self.duration_s,
            "target": self.target,
            "params": dict(self.params),
            "event_id": self.event_id,
        }


@dataclasses.dataclass
class FaultPlan:
    """A named list of fault events plus the seed that concretizes them."""

    name: str = "plan"
    seed: int = 0
    events: List[FaultEvent] = dataclasses.field(default_factory=list)
    expanded: bool = False

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "FaultPlan":
        events = []
        for raw in data.get("events", []):
            raw = dict(raw)
            kind = raw.pop("kind")
            events.append(FaultEvent(
                kind=kind,
                at_s=raw.pop("at_s", 0.0),
                duration_s=raw.pop("duration_s", 0.0),
                target=raw.pop("target", None),
                params=dict(raw.pop("params", {}) or {}),
                event_id=raw.pop("event_id", ""),
            ))
            if raw:
                raise ValueError(f"unknown event fields: {sorted(raw)}")
        return cls(name=data.get("name", "plan"),
                   seed=int(data.get("seed", 0)),
                   events=events,
                   expanded=bool(data.get("expanded", False)))

    @classmethod
    def from_json(cls, path: str | Path) -> "FaultPlan":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def expand(self, seed: Optional[int] = None) -> "FaultPlan":
        """Resolve randomized fields and assign event ids.

        Deterministic: one ``random.Random(seed)`` consumed in event order
        with a fixed draw pattern, so the same (plan, seed) always yields a
        deep-equal schedule.  Expanding an already-expanded plan is the
        identity (ids and values are kept).
        """
        if self.expanded:
            return self
        seed = self.seed if seed is None else int(seed)
        rng = random.Random(seed)
        out = []
        for i, ev in enumerate(self.events):
            at_s = float(_resolve(ev.at_s, rng))
            duration_s = float(_resolve(ev.duration_s, rng))
            target = _resolve(ev.target, rng)
            params = {k: _resolve(v, rng) for k, v in sorted(ev.params.items())}
            out.append(dataclasses.replace(
                ev, at_s=at_s, duration_s=duration_s, target=target,
                params=params, event_id=ev.event_id or f"{ev.kind}:{i:03d}"))
        return FaultPlan(name=self.name, seed=seed, events=out, expanded=True)

    def filter(self, planes: Sequence[str] = (),
               kinds: Sequence[str] = ()) -> "FaultPlan":
        """Sub-plan keeping only events on the given planes/kinds (event ids
        are preserved — filter after :meth:`expand`)."""
        keep = [ev for ev in self.events
                if (not planes or FAULT_KINDS[ev.kind] in planes)
                and (not kinds or ev.kind in kinds)]
        return FaultPlan(name=self.name, seed=self.seed, events=keep,
                         expanded=self.expanded)

    def planes(self) -> Tuple[str, ...]:
        return tuple(sorted({FAULT_KINDS[ev.kind] for ev in self.events}))

    def kinds(self) -> Tuple[str, ...]:
        return tuple(sorted({ev.kind for ev in self.events}))

    def horizon_s(self) -> float:
        """Latest event end — the minimum soak length that covers the plan."""
        return max([ev.end_s for ev in self.events], default=0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "seed": self.seed,
            "expanded": self.expanded,
            "events": [ev.to_dict() for ev in self.events],
        }

    def save(self, path: str | Path) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
