"""Soak invariants: the contracts the repo pins piecemeal, checked centrally.

Consumes the merged metrics records of a chaos soak (serving + trainer
planes) plus a ``facts`` dict of driver-side observations that aren't in the
metrics stream (bit-exact resume verdict, which planes actually ran), and
returns one :class:`InvariantResult` per contract:

* ``zero_dropped_requests`` — graceful degradation means clients see 429
  sheds and retries, never errors: every serving slice has
  ``serving_error_rate == 0`` (and zero deadline misses), neither the fleet
  nor the service router exhausted retries, and the async trajectory queue
  dropped nothing.
* ``zero_steady_recompiles`` — every ``*steady_state_recompiles`` gauge in
  every record is 0: faults must not knock compiled programs off their
  signatures.
* ``staleness_p95_le_1`` — the async overlap's staleness budget holds under
  injected delays: last ``staleness_learner_steps_p95`` ≤ the run's budget.
  The budget is read from the records' own ``store_staleness_budget`` gauge
  (the trajectory store self-describes it), falling back to
  ``facts["staleness_budget"]`` and finally 1.0 — so pre-scale-out records
  keep their original ≤ 1 contract.  The name keeps the historical ``le_1``
  even at B > 1: it is the same contract with the bound generalized.
* ``bit_exact_resume`` — the kill-and-relaunch trainer converges to the
  byte-identical final state of an uninterrupted twin (driver-computed).
* ``incident_attribution`` — the correlator's verdict
  (telemetry/incidents.py): an armed soak yields incidents for the injected
  faults with 100% attribution and zero unexplained open incidents; a
  disarmed soak yields zero incidents.
* ``slo_burn_recovery`` — after the last fault clears, every ``slo_*_burn``
  gauge in the final fleet record is back under 1.0 (budget no longer
  burning).

An invariant whose plane didn't run reports ``ok`` with a "skipped" detail —
absence of data is only a failure when the plan said the plane would run.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str
    skipped: bool = False

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


def _skip(name: str, why: str) -> InvariantResult:
    return InvariantResult(name, True, f"skipped: {why}", skipped=True)


def _num(record: dict, key: str) -> Optional[float]:
    v = record.get(key)
    return float(v) if isinstance(v, (int, float)) and not isinstance(v, bool) else None


def check_invariants(records: List[dict],
                     facts: Optional[Dict[str, object]] = None,
                     ) -> List[InvariantResult]:
    facts = dict(facts or {})
    out: List[InvariantResult] = []
    metrics = [r for r in records
               if "chaos" not in r and "anomaly" not in r
               and "trace" not in r and "emergency_checkpoint" not in r
               and "incident" not in r and "ts" not in r]

    # --- zero dropped requests -------------------------------------------
    bad: List[str] = []
    serving = [r for r in metrics if "serving_error_rate" in r]
    for r in serving:
        for key in ("serving_error_rate", "serving_deadline_miss_rate"):
            v = _num(r, key)
            if v:
                bad.append(f"{key}={v:.4f}")
    exhausted = max((_num(r, "fleet_retries_exhausted") or 0.0)
                    for r in metrics) if metrics else 0.0
    if exhausted:
        bad.append(f"fleet_retries_exhausted={exhausted:g}")
    # the federation tier: a request that exhausted its sibling-host
    # failovers surfaced to the client as an error — that IS a drop
    r_exhausted = max((_num(r, "router_retries_exhausted") or 0.0)
                      for r in metrics) if metrics else 0.0
    if r_exhausted:
        bad.append(f"router_retries_exhausted={r_exhausted:g}")
    drops = max((_num(r, "async_queue_drops") or 0.0)
                for r in metrics) if metrics else 0.0
    if drops:
        bad.append(f"async_queue_drops={drops:g}")
    if not serving and not facts.get("expect_serving", True):
        out.append(_skip("zero_dropped_requests", "no serving records"))
    else:
        out.append(InvariantResult(
            "zero_dropped_requests", not bad,
            "; ".join(bad) if bad
            else f"clean across {len(serving)} serving slices "
                 f"(sheds/429s are graceful, not drops)"))

    # --- zero steady-state recompiles ------------------------------------
    recompiled: List[str] = []
    for r in metrics:
        for key, v in r.items():
            if key.endswith("steady_state_recompiles") \
                    and isinstance(v, (int, float)) and v:
                recompiled.append(f"{key}={v:g}")
    out.append(InvariantResult(
        "zero_steady_recompiles", not recompiled,
        "; ".join(sorted(set(recompiled))) if recompiled
        else "every *steady_state_recompiles gauge is 0"))

    # --- async staleness --------------------------------------------------
    stale = [r for r in metrics if "staleness_learner_steps_p95" in r]
    if not stale:
        if facts.get("expect_async", False):
            out.append(InvariantResult(
                "staleness_p95_le_1", False,
                "async plane expected but emitted no staleness gauges"))
        else:
            out.append(_skip("staleness_p95_le_1", "no async records"))
    else:
        p95 = _num(stale[-1], "staleness_learner_steps_p95") or 0.0
        # the store self-describes its budget; old (pre-scale-out) records
        # carry no gauge and keep the original <= 1 bound
        budget = next(
            (_num(r, "store_staleness_budget") for r in reversed(stale)
             if _num(r, "store_staleness_budget") is not None),
            None)
        if budget is None:
            budget = float(facts.get("staleness_budget", 1.0) or 1.0)
        out.append(InvariantResult(
            "staleness_p95_le_1", p95 <= budget,
            f"staleness_learner_steps_p95={p95:g} <= budget {budget:g} "
            f"(last async record)" if p95 <= budget else
            f"staleness_learner_steps_p95={p95:g} exceeds budget {budget:g}"))

    # --- bit-exact resume -------------------------------------------------
    verdict = facts.get("bit_exact_resume")
    if verdict is None:
        if facts.get("expect_kill", False):
            out.append(InvariantResult(
                "bit_exact_resume", False,
                "trainer_kill scheduled but no resume verdict recorded"))
        else:
            out.append(_skip("bit_exact_resume", "no kill event in plan"))
    else:
        out.append(InvariantResult(
            "bit_exact_resume", bool(verdict),
            "killed+resumed run matches uninterrupted twin bit-for-bit"
            if verdict else
            "resumed final state differs from uninterrupted twin"))

    # --- incident attribution ---------------------------------------------
    # The correlator's verdict (telemetry/incidents.py): every incident of an
    # armed soak must be attributed to an injected fault, and zero
    # unexplained incidents may remain open — an unattributed incident means
    # something broke that nobody injected, which fails the soak.  A clean
    # (disarmed) soak must produce zero incidents at all.
    incident_summary = facts.get("incident_summary")
    if incident_summary is None:
        if facts.get("expect_incidents", False):
            out.append(InvariantResult(
                "incident_attribution", False,
                "faults fired but the correlator recorded no verdict"))
        else:
            out.append(_skip("incident_attribution", "correlator did not run"))
    else:
        total = float(incident_summary.get("incident_total", 0.0))
        unexplained = float(incident_summary.get("incident_unexplained", 0.0))
        opened = float(incident_summary.get("incident_open", 0.0))
        if facts.get("expect_incidents", False):
            ok = total > 0 and unexplained == 0 and opened == 0
            detail = (f"{total:g} incidents, 100% attributed, none left open"
                      if ok else
                      f"total={total:g} unexplained={unexplained:g} "
                      f"open={opened:g} (armed soak demands incidents for "
                      f"injected faults, all attributed, none open)")
        else:
            ok = total == 0
            detail = ("clean soak: zero incidents" if ok else
                      f"{total:g} incidents on a disarmed soak "
                      f"({unexplained:g} unexplained)")
        out.append(InvariantResult("incident_attribution", ok, detail))

    # a disarmed golden twin ran alongside: it must be incident-quiet —
    # symptoms on a run with no faults armed mean the stack itself is sick
    clean = facts.get("clean_incident_summary")
    if clean is not None:
        total = float(clean.get("incident_total", 0.0))
        out.append(InvariantResult(
            "disarmed_twin_quiet", total == 0,
            "disarmed golden twin produced zero incidents" if total == 0
            else f"{total:g} incident(s) on the disarmed golden twin"))

    # --- SLO burn recovery ------------------------------------------------
    burns = [r for r in metrics
             if any(k.endswith("_burn") for k in r)]
    if not burns:
        if facts.get("expect_serving", True):
            out.append(InvariantResult(
                "slo_burn_recovery", False,
                "serving plane expected but emitted no slo_*_burn gauges"))
        else:
            out.append(_skip("slo_burn_recovery", "no SLO records"))
    else:
        last = burns[-1]
        hot = {k: v for k, v in last.items()
               if k.endswith("_burn") and isinstance(v, (int, float))
               and v >= 1.0}
        out.append(InvariantResult(
            "slo_burn_recovery", not hot,
            "; ".join(f"{k}={v:g}" for k, v in sorted(hot.items())) if hot
            else "all slo_*_burn < 1.0 in the final fleet record"))

    return out


def all_green(results: List[InvariantResult]) -> bool:
    return all(r.ok for r in results)
