#!/usr/bin/env python
"""Train on SMAC maps (StarCraft II combat).

Equivalent of the reference entry point
``mat_src/mat/scripts/train/train_smac.py`` (+ ``train_smac.sh`` recipe).
Default backend is the pure-JAX combat stand-in
(``mat_dcml_tpu/envs/smac/smaclite.py``) — vmapped on device, no game binary.
``--backend sc2`` drives the real game through the host-process vec-env
bridge (requires the external smac package + an SC2 install).

Usage:
  python train_smac.py --map_name 3m --algorithm_name mat \
      --num_env_steps 500000 --n_rollout_threads 32
  python train_smac.py --map_name 2s3z --algorithm_name mappo
"""

import argparse
import dataclasses
import sys

from mat_dcml_tpu.utils.platform import apply_platform_override

apply_platform_override()

from mat_dcml_tpu.config import parse_cli_with_extras
from mat_dcml_tpu.envs.smac import SMACLiteConfig, SMACLiteEnv, map_param_registry
from mat_dcml_tpu.training.smac_runner import SMACRunner


def main(argv=None):
    extras = argparse.ArgumentParser(add_help=False)
    extras.add_argument("--map_name", type=str, default="3m",
                        choices=sorted(map_param_registry))
    extras.add_argument("--backend", type=str, default="smaclite",
                        choices=("smaclite", "sc2"))
    # per-episode agent-order shuffling (Random_StarCraft2_Env equivalent)
    extras.add_argument("--random_order", action="store_true")
    run, ppo, ns = parse_cli_with_extras(argv, extras=extras, overrides={
        "env_name": "StarCraft2", "episode_length": 60,
    })
    run = dataclasses.replace(run, scenario=ns.map_name)
    if ns.backend == "sc2":
        raise SystemExit(
            "--backend sc2 needs the external smac package + an SC2 install "
            "(not bundled); wire SMACHostEnv through ShareSubprocVecEnv + "
            "HostRolloutCollector (envs/smac/host.py docstring)."
        )
    env = SMACLiteEnv(SMACLiteConfig(map_name=ns.map_name))
    if ns.random_order:
        from mat_dcml_tpu.envs.permute import AgentPermutationWrapper
        env = AgentPermutationWrapper(env)
    runner = SMACRunner(run, ppo, env)
    print(f"algorithm={run.algorithm_name} env=SMAC/{ns.map_name} "
          f"agents={env.n_agents} episodes={run.episodes} "
          f"devices={len(__import__('jax').devices())}")
    state, _ = runner.train_loop()
    print("final eval:", runner.evaluate(state, n_episodes=run.eval_episodes))


if __name__ == "__main__":
    main(sys.argv[1:])
