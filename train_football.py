#!/usr/bin/env python
"""Train MAT on Google Research Football through the host-process bridge.

Equivalent of the reference entry point
``mat_src/mat/scripts/train/train_football.py`` (+ ``train_football.sh``):
gfootball workers in subprocesses (``ShareSubprocVecEnv``), encoded features
and shaped rewards (``mat_dcml_tpu/envs/football/encoders.py``), jitted MAT
policy on device, goal-difference metrics.

Requires the external gfootball package (not bundled) — the entry point
exists so a user with gfootball installed runs it unmodified.

Usage:
  python train_football.py --scenario academy_3_vs_1_with_keeper \
      --n_agent 3 --n_rollout_threads 8
"""

import argparse
import sys

from mat_dcml_tpu.utils.platform import apply_platform_override

apply_platform_override()

from mat_dcml_tpu.config import parse_cli_with_extras
from mat_dcml_tpu.envs.football import FootballHostEnv
from mat_dcml_tpu.envs.vec_env import ShareDummyVecEnv, ShareSubprocVecEnv
from mat_dcml_tpu.training.football_runner import FootballRunner


def main(argv=None):
    extras = argparse.ArgumentParser(add_help=False)
    extras.add_argument("--n_agent", type=int, default=3)
    extras.add_argument("--rewards", type=str, default="scoring")
    extras.add_argument("--envs_per_worker", type=int, default=1)
    run, ppo, ns = parse_cli_with_extras(argv, extras=extras, overrides={
        "env_name": "football", "scenario": "academy_3_vs_1_with_keeper",
        "episode_length": 200,
    })

    # gate BEFORE forking bridge workers: a missing gfootball would otherwise
    # kill every worker during env construction and surface as a pipe error
    try:
        import gfootball  # noqa: F401
    except ImportError:
        raise SystemExit(
            "train_football.py needs the external gfootball package (not "
            "bundled in this image). The encoders and runner are tested "
            "against fake backends (tests/test_football.py); install "
            "gfootball to drive the real game through the host bridge."
        )

    def make_env(scenario=run.scenario, n=ns.n_agent, rew=ns.rewards):
        return FootballHostEnv(scenario=scenario, n_agents=n, rewards=rew)

    fns = [make_env for _ in range(run.n_rollout_threads)]
    vec = (
        ShareDummyVecEnv(fns)
        if run.n_rollout_threads == 1
        else ShareSubprocVecEnv(fns, envs_per_worker=ns.envs_per_worker)
    )
    runner = FootballRunner(run, ppo, vec)
    print(f"algorithm={run.algorithm_name} env=football/{run.scenario} "
          f"agents={ns.n_agent} episodes={run.episodes}")
    try:
        runner.train_loop()
    finally:
        vec.close()


if __name__ == "__main__":
    main(sys.argv[1:])
