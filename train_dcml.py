#!/usr/bin/env python
"""Train MAT on the DCML worker-selection env (TPU-native).

Equivalent of the reference entry point ``DCML_MAT_Train.py`` — same default
recipe (8 env batch, 1M steps, episode_length 50, lr 5e-5, ppo_epoch 15,
4 minibatches, valuenorm), minus the subprocess vec-envs and run-dir/wandb
boilerplate.  Metrics stream to ``<run_dir>/metrics.jsonl``.

Usage:
  python train_dcml.py                      # full recipe
  python train_dcml.py --num_env_steps 40000 --n_rollout_threads 4
"""

import sys

from mat_dcml_tpu.utils.platform import apply_platform_override

apply_platform_override()

from mat_dcml_tpu.config import parse_cli
from mat_dcml_tpu.parallel.distributed import init_distributed, is_primary
from mat_dcml_tpu.training.runner import DCMLRunner


def main(argv=None):
    # multi-host: MAT_DCML_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID env vars
    # (or TPU-pod auto-detection); single-process no-op
    init_distributed()
    run, ppo = parse_cli(argv)
    log = print if is_primary() else (lambda *a, **k: None)
    runner = DCMLRunner(run, ppo, log_fn=log)
    log(f"algorithm={run.algorithm_name} env={run.env_name}/{run.scenario} "
        f"episodes={run.episodes} devices={len(__import__('jax').devices())} "
        f"processes={__import__('jax').process_count()}")
    runner.train_loop()


if __name__ == "__main__":
    main(sys.argv[1:])
