#!/usr/bin/env python
"""Train on multi-agent MuJoCo (factorized robots, continuous control).

Equivalent of the reference entry point
``mat_src/mat/scripts/train/train_mujoco.py`` (+ ``train_mujoco.sh`` incl.
its fault-injection flags).  Default backend is the pure-JAX stand-in
dynamics over the same obsk joint factorization
(``mat_dcml_tpu/envs/mamujoco/lite.py``); ``--backend gym`` drives real
MuJoCo through the host-process bridge (requires gymnasium+mujoco).

Usage:
  python train_mujoco.py --scenario HalfCheetah-v2 --agent_conf 2x3
  python train_mujoco.py --scenario Ant-v2 --agent_conf 2x4d --faulty_node 1 \
      --eval_faulty_node 0,1
"""

import argparse
import dataclasses
import sys

from mat_dcml_tpu.utils.platform import apply_platform_override

apply_platform_override()

from mat_dcml_tpu.config import parse_cli_with_extras
from mat_dcml_tpu.envs.mamujoco import MJLiteConfig, MJLiteEnv
from mat_dcml_tpu.training.mujoco_runner import MujocoRunner


def main(argv=None):
    extras = argparse.ArgumentParser(add_help=False)
    extras.add_argument("--agent_conf", type=str, default="2x3")
    extras.add_argument("--agent_obsk", type=int, default=1)
    extras.add_argument("--faulty_node", type=int, default=-1)
    extras.add_argument("--eval_faulty_node", type=str, default="")
    extras.add_argument("--backend", type=str, default="lite", choices=("lite", "gym"))
    # per-episode agent-order shuffling (random_mujoco_multi equivalent)
    extras.add_argument("--random_order", action="store_true")
    # the robot rides the shared --scenario flag (RunConfig.scenario)
    run, ppo, ns = parse_cli_with_extras(argv, extras=extras, overrides={
        "env_name": "mujoco", "scenario": "HalfCheetah-v2", "episode_length": 50,
    })
    ns.scenario = run.scenario
    run = dataclasses.replace(run, scenario=f"{ns.scenario}_{ns.agent_conf}")
    if ns.backend == "gym":
        return _main_gym(run, ppo, ns)
    env = MJLiteEnv(MJLiteConfig(
        scenario=ns.scenario, agent_conf=ns.agent_conf,
        agent_obsk=ns.agent_obsk, episode_length=run.episode_length,
    ))
    runner = MujocoRunner(run, ppo, env, faulty_node=ns.faulty_node,
                          random_order=ns.random_order)
    print(f"algorithm={run.algorithm_name} env=mujoco/{ns.scenario}/{ns.agent_conf} "
          f"agents={env.n_agents} episodes={run.episodes} "
          f"devices={len(__import__('jax').devices())}")
    state, _ = runner.train_loop()
    print("eval (healthy):", runner.evaluate(state, n_steps=run.episode_length))
    if ns.eval_faulty_node:
        nodes = [int(x) for x in ns.eval_faulty_node.split(",") if x]
        print("faulty sweep:", runner.evaluate_faulty_sweep(
            state, nodes, n_steps=run.episode_length))


def _main_gym(run, ppo, ns):
    """Real MuJoCo through the host bridge (``mujoco_multi.py:39-260``)."""
    # gate BEFORE forking bridge workers (same reasoning as train_football.py)
    try:
        import gymnasium  # noqa: F401
        import mujoco  # noqa: F401
    except ImportError as err:
        raise SystemExit(
            "--backend gym needs gymnasium + mujoco; use --backend lite for "
            "the binary-free pure-JAX dynamics"
        ) from err
    import re

    from mat_dcml_tpu.envs.mamujoco.env import MujocoMultiHostEnv
    from mat_dcml_tpu.envs.vec_env import ShareDummyVecEnv, ShareSubprocVecEnv
    from mat_dcml_tpu.training.mujoco_runner import MujocoHostRunner

    if ns.random_order:
        raise SystemExit("--random_order is a pure-JAX wrapper; use --backend lite")
    # the reference pins gym==0.21 robots (HalfCheetah-v2); gymnasium ships
    # v4/v5 of the same models — map old version suffixes forward
    scenario = re.sub(r"-v[0-3]$", "-v4", ns.scenario)

    def make_env(i, scenario=scenario, conf=ns.agent_conf, obsk=ns.agent_obsk,
                 limit=run.episode_length, seed0=run.seed):
        def thunk():
            return MujocoMultiHostEnv(
                scenario, conf, agent_obsk=obsk, episode_limit=limit,
                seed=seed0 * 1000 + i,
            )
        return thunk

    fns = [make_env(i) for i in range(run.n_rollout_threads)]
    vec = ShareDummyVecEnv(fns) if run.n_rollout_threads == 1 else ShareSubprocVecEnv(fns)
    try:
        # construct inside the try: a raising constructor (thread-count
        # mismatch, non-MAT algorithm) must not leak the spawned workers
        runner = MujocoHostRunner(
            run, ppo, vec, faulty_node=ns.faulty_node,
            # index-parameterized eval factory: each eval env gets its own seed
            eval_env_fn=lambda i=0: make_env(run.n_rollout_threads + i)(),
        )
        print(f"algorithm={run.algorithm_name} env=mujoco-gym/{scenario}/{ns.agent_conf} "
              f"agents={vec.n_agents} episodes={run.episodes}")
        state, _ = runner.train_loop()
        print("eval (healthy):", runner.evaluate(state, n_steps=run.episode_length))
        if ns.eval_faulty_node:
            nodes = [int(x) for x in ns.eval_faulty_node.split(",") if x]
            print("faulty sweep:", runner.evaluate_faulty_sweep(
                state, nodes, n_steps=run.episode_length))
    finally:
        vec.close()


if __name__ == "__main__":
    main(sys.argv[1:])
